"""Train configuration dataclasses.

Parity: reference python/ray/air/config.py (ScalingConfig:102, RunConfig,
CheckpointConfig, FailureConfig), re-pointed at TPU concepts: instead of
GPUs-per-worker the scaling config speaks hosts x chips and optionally a
mesh layout (ray_tpu.parallel.MeshSpec) that the trainer materialises on
the worker group.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclasses.dataclass
class ElasticConfig:
    """Elastic, preemption-tolerant data-parallel training (r14).

    With ``ScalingConfig(elastic=ElasticConfig(...))``, ``fit()``
    survives node loss AND gain mid-run: the worker group reshapes (the
    dp mesh shrinks to the surviving worker count or grows when a
    replacement host joins), state auto-restores from the latest
    registered checkpoint — delivered to (re)joining workers through
    the r8 broadcast tree instead of N head pulls — and step accounting
    stays exact (a restored run's replayed reports are deduped by step;
    dataset shards re-split deterministically). On a preemption notice
    (autoscaler drain) the trainer flushes a checkpoint and
    acknowledges the drain so the node is released only after state is
    safe.

    min_workers: reshape floor — below this fit() waits for capacity
        (RAY_TPU_ELASTIC_CAPACITY_TIMEOUT_S) instead of running with
        too small a mesh.
    max_workers: reshape ceiling; 0 = ScalingConfig.num_workers.
    checkpoint_every_n_steps: cadence the worker loop should honor via
        ``train.should_checkpoint(step)`` (fires on step n-1, 2n-1, …,
        plus whenever the trainer requests a flush — drain notices,
        pre-grow). 0 leaves checkpointing entirely to the user loop,
        at the cost of replaying from the last user checkpoint on
        reshape.
    broadcast_restore: deliver the restore checkpoint via
        ``ray_tpu.broadcast`` (source serves <= fanout transfers) when
        remote agents are present; off = every worker pulls from the
        head.
    """
    min_workers: int = 1
    max_workers: int = 0
    checkpoint_every_n_steps: int = 1
    broadcast_restore: bool = True

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if self.max_workers and self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers={self.max_workers} < "
                f"min_workers={self.min_workers}")
        if self.checkpoint_every_n_steps < 0:
            raise ValueError("checkpoint_every_n_steps must be >= 0")


@dataclasses.dataclass
class ScalingConfig:
    """How many training workers and what each holds.

    num_workers: one worker process per TPU host (each worker is one
    jax.distributed process owning that host's chips). use_tpu=False
    runs CPU-only workers (CI, debugging).
    """
    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 0           # 0 = all visible chips
    resources_per_worker: Optional[Dict[str, float]] = None
    mesh: Optional[MeshSpec] = None     # global mesh over all workers
    placement_strategy: str = "PACK"
    # TPU pod-slice mode: topology (e.g. "v4-32") makes the trainer
    # reserve the whole slice as a STRICT_SPREAD placement group (one
    # worker per slice host, head bundle on rank 0 — the reference's
    # pod-slice scheduling, _private/accelerators/tpu.py:334-397).
    topology: Optional[str] = None
    pod_name: Optional[str] = None
    # Elastic mode (r14): reshape the group on node loss/gain instead
    # of whole-group restart-in-place; num_workers becomes the DESIRED
    # world size within [elastic.min_workers, elastic.max_workers].
    elastic: Optional[ElasticConfig] = None

    def __post_init__(self):
        if self.elastic is not None:
            # cross-validate against the EFFECTIVE ceiling now (0 means
            # num_workers): an impossible floor would otherwise surface
            # only as a misleading capacity timeout at fit() time
            eff_max = self.elastic.max_workers or self.num_workers
            if self.elastic.min_workers > eff_max:
                raise ValueError(
                    f"elastic.min_workers={self.elastic.min_workers} "
                    f"exceeds the effective max_workers={eff_max} "
                    f"(= num_workers when elastic.max_workers is 0)")
        if self.topology is not None and self.elastic is not None:
            # A pod slice provisions and dies ATOMICALLY — there is no
            # per-host shrink to reshape around, and the elastic group
            # builder has no slice bundle pinning. Fail loudly instead
            # of silently dropping the slice placement.
            raise ValueError(
                "elastic= is not supported with topology= (a pod "
                "slice preempts atomically; run elastic across "
                "single-host node types instead)")
        if self.topology is not None:
            from ray_tpu._private.accelerators.tpu import num_hosts
            hosts = num_hosts(self.topology)
            if self.num_workers not in (1, hosts):
                raise ValueError(
                    f"num_workers={self.num_workers} contradicts "
                    f"topology {self.topology} ({hosts} hosts)")
            self.num_workers = hosts
            self.use_tpu = True
            self.placement_strategy = "STRICT_SPREAD"

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            if self.topology is not None:
                from ray_tpu._private.accelerators.tpu import chips_per_host
                res.setdefault("TPU", float(chips_per_host(self.topology)))
            else:
                res.setdefault("TPU", float(self.chips_per_worker or 1))
        return res

    def worker_bundles(self) -> Optional[list]:
        """Explicit per-rank bundles for pod-slice mode (else None)."""
        if self.topology is None:
            return None
        from ray_tpu.util.accelerators.tpu import slice_bundles
        base = self.worker_resources()
        bundles = slice_bundles(self.topology, self.pod_name,
                                cpus_per_host=base.get("CPU", 1.0))
        return bundles


@dataclasses.dataclass
class PipelineConfig:
    """MPMD pipeline-parallel training (r13): the layer stack is
    partitioned across `pipeline_stages` worker GROUPS (remainder
    layers to the last stage, parallel.pipeline.partition_layers), and
    activations/grads stream stage-to-stage over compiled-DAG channels
    with a 1F1B (or GPipe) microbatch schedule — each stage is its own
    set of processes owning its own slice, per "Scaling Deep Learning
    Training with MPMD Pipeline Parallelism" (PAPERS.md).

    init_params: layer-stacked pytree, leaves (L, ...).
    stage_fn(stage_params, x, *consts) -> y: applies ONE stage's
        sub-stack (leaves (L_s, ...), possibly ragged across stages —
        MPMD stages are independent programs).
    loss_fn(y, targets) -> scalar summed microbatch loss (the 1F1B
        contract shared with parallel.pipeline.pipeline_grads_1f1b).
    batch_fn(step) -> (x, targets): the per-step global batch.
    update_fn(params, grads, step) -> params: per-stage optimizer
        applied to that stage's slice with grads already averaged over
        microbatches; None = SGD with `lr`.
    transport: "shm" (same-box rings) | "wire" (cross-host, tensors
        over the Envelope raw zero-copy path) | "auto" (wire for
        cross-host edges only).
    ring_depth: channel ring slots (None -> RAY_TPU_CHANNEL_RING_DEPTH;
        >= 2 overlaps a stage's sends with its neighbors' compute).
    """
    init_params: Any = None
    stage_fn: Any = None
    loss_fn: Any = None
    batch_fn: Any = None
    steps: int = 1
    consts: tuple = ()
    num_microbatches: int = 4
    schedule: str = "1f1b"
    transport: str = "shm"
    ring_depth: Optional[int] = None
    channel_capacity_bytes: int = 4 << 20
    workers_per_stage: int = 1
    update_fn: Any = None
    lr: float = 1e-2

    def __post_init__(self):
        if self.schedule not in ("1f1b", "gpipe"):
            raise ValueError("schedule must be 1f1b|gpipe")
        if self.transport not in ("shm", "wire", "auto"):
            raise ValueError("transport must be shm|wire|auto")
        if self.num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")


@dataclasses.dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None        # None = keep all
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be max|min")


@dataclasses.dataclass
class FailureConfig:
    """Whole-group restart-from-checkpoint semantics (reference
    backend_executor.py:759-786): max_failures < 0 means unlimited."""
    max_failures: int = 0


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None       # defaults to ~/ray_tpu_results
    checkpoint_config: CheckpointConfig = dataclasses.field(
        default_factory=CheckpointConfig)
    failure_config: FailureConfig = dataclasses.field(
        default_factory=FailureConfig)
    # Max seconds between report() rounds before the group is declared
    # hung. None = wait forever (first steps of big models can spend
    # many minutes in XLA compilation).
    worker_poll_timeout: Optional[float] = None


@dataclasses.dataclass
class Result:
    """What JaxTrainer.fit returns (reference train/base_trainer Result)."""
    metrics: Dict[str, Any]
    checkpoint: Optional["Checkpoint"]  # noqa: F821 (train.checkpoint)
    path: str
    metrics_history: list = dataclasses.field(default_factory=list)
    error: Optional[BaseException] = None
    # trial config when produced by a Tune sweep (reference Result.config)
    config: Optional[Dict[str, Any]] = None
    # non-scalar outputs (MPMD pipeline mode returns the reassembled
    # layer-major params here; metrics stay scalar-only)
    artifacts: Optional[Dict[str, Any]] = None
