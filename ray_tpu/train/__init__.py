"""ray_tpu.train: distributed training orchestration (Ray Train parity).

The minimum end-to-end slice of SURVEY.md §7 step 5: JaxTrainer fans a
user `train_loop_per_worker` out to a WorkerGroup of actors, wires them
into one jax.distributed SPMD program (JaxBackend), streams report()
results back, manages checkpoints with retention, and restarts the whole
group from the latest checkpoint on failure.

Reference mapping:
- JaxTrainer       <- train/data_parallel_trainer.py + backend_executor.py
- Backend/JaxConfig<- train/backend.py + train/torch/xla/config.py
- report/get_context <- train/_internal/session.py
- Checkpoint/CheckpointManager <- train/_checkpoint.py, checkpoint_manager.py
- ScalingConfig etc <- air/config.py
"""
from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxConfig  # noqa: F401
from ray_tpu.train.checkpoint import (Checkpoint, CheckpointManager,  # noqa: F401
                                      load_pytree, save_pytree)
from ray_tpu.train.config import (CheckpointConfig, ElasticConfig,  # noqa: F401
                                  FailureConfig, PipelineConfig, Result,
                                  RunConfig, ScalingConfig)
from ray_tpu.train.session import (get_checkpoint, get_context,  # noqa: F401
                                   get_dataset_shard,
                                   make_temp_checkpoint_dir, report,
                                   should_checkpoint)
from ray_tpu.train.trainer import JaxTrainer  # noqa: F401
from ray_tpu.train.worker_group import RayTrainWorker, WorkerGroup  # noqa: F401
