"""MPMD pipeline parallelism: stage-per-worker-group training over
compiled-DAG channels (r13).

MULTICHIP_r05 proved pp-axis parity INSIDE one process
(parallel/pipeline.py: SPMD GPipe/1F1B via shard_map + ppermute). This
module is the pod-scale shape from "Scaling Deep Learning Training
with MPMD Pipeline Parallelism" (PAPERS.md): every pipeline stage is
its OWN worker group running its OWN program on its own slice of the
layer stack, and activations/cotangents stream stage-to-stage over the
compiled-DAG channel layer — multi-slot rings (shm same-box, the wire
transport cross-host) whose depth >= 2 double-buffers each edge, so a
stage computes microbatch m+1 while m is still in flight to its
neighbor. The driver never touches an activation: it feeds microbatch
inputs to stage 0, targets to the last stage, and reads one loss per
step ("Exploring the limits of Concurrency in ML Training on Google
TPUs": the control plane stays off the hot path).

Schedules: classic 1F1B (stage s runs S-1-s warmup forwards, then
alternates forward/backward, then drains — at most S-s stashed
activations per stage independent of M) and GPipe fill-drain (all M
forwards, then all M backwards) as the fallback. Stage backwards
recompute their forward from the saved stage input (remat), the same
trade the SPMD 1F1B schedule makes.

MPMD makes two things free that are structurally hard in SPMD mode:
ragged stages (layer counts need not divide the stage count — the
shared `partition_layers` helper assigns the remainder to the last
stage) and per-stage compilation (each stage jits only its own
sub-stack).

Verification is the r9 tracing plane: stage loops run under one trace
id, forward/backward compute spans and channel wait/write/read spans
land in each process's flight recorder, and
`util.tracing.task_timeline()` renders the cross-process Perfetto
timeline where overlap (and the bubble fraction, `bubble_fraction()`)
is directly visible.
"""
from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu._private import tracing_plane as _tp
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelTimeout, _ring_depth)
from ray_tpu.experimental.dag_channels import AbortFlag, LoopWatchdog
from ray_tpu.experimental.wire_channel import WireChannel, _my_ip
from ray_tpu.parallel.pipeline import partition_layers, slice_stage


def _serve_many(_instance, specs: list) -> list:
    """__rtpu_apply__ body: bind several wire-channel servers in one
    actor round trip; returns their addresses in spec order."""
    from ray_tpu.experimental.wire_channel import serve_channel
    return [serve_channel(name, cap, nr, depth, label).addr
            for (name, cap, nr, depth, label) in specs]


def _host_info(_instance) -> str:
    return _my_ip()


def _stage_loop(_instance, stage: int, n_stages: int, stage_params,
                stage_fn, loss_fn, consts, schedule: str, M: int,
                steps: int, in_ch, tgt_ch, out_ch, gin_ch, gout_ch,
                loss_ch, abort, update_fn, lr: float, trace_root: int):
    """Runs INSIDE a stage worker (one long-lived call): the whole
    training run for this stage — per step, a 1F1B/GPipe microbatch
    schedule over the neighbor channels, then the local optimizer
    update on this stage's params. Returns the final stage params."""
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    if trace_root and _tp.enabled():
        _tp.set_current(trace_root, 0)
    last = stage == n_stages - 1

    reader = in_ch.reader(0)
    tgt_reader = tgt_ch.reader(0) if tgt_ch is not None else None
    writer = out_ch.writer() if out_ch is not None else None
    gin = gin_ch.reader(0) if gin_ch is not None else None
    gout = gout_ch.writer() if gout_ch is not None else None
    loss_w = loss_ch.writer() if loss_ch is not None else None

    def bounded(fn, *a):
        while True:
            try:
                return fn(*a, timeout=1.0)
            except ChannelTimeout:
                if abort is not None and abort.is_set():
                    raise ChannelClosed("aborted") from None

    consts = tuple(consts)
    fwd = jax.jit(lambda p, x: stage_fn(p, x, *consts))

    def _vjp(p, x, cot):
        _, vjp_fn = jax.vjp(lambda pp, xx: stage_fn(pp, xx, *consts),
                            p, x)
        return vjp_fn(cot)
    bwd = jax.jit(_vjp)
    if last:
        def _loss(p, x, t):
            return loss_fn(stage_fn(p, x, *consts), t)
        loss_grads = jax.jit(jax.value_and_grad(_loss, argnums=(0, 1)))

    params = stage_params
    try:
        for step in range(steps):
            grads = jax.tree_util.tree_map(jnp.zeros_like, params)
            loss_acc = 0.0
            saved: deque = deque()
            # 1F1B: stage s injects S-1-s warmup forwards, then
            # alternates 1F1B, then drains — its stash stays O(S-s).
            # GPipe: all M forwards first (stash O(M)).
            W = M if schedule == "gpipe" else min(M, n_stages - 1 - stage)

            def fwd_one():
                x = bounded(reader.read)
                if last:
                    # the last stage's forward is fused into its
                    # backward (loss_grads computes both in one jit);
                    # here it only stashes the pair
                    t = bounded(tgt_reader.read)
                    saved.append((x, t))
                    return
                with _tp.span("stage", f"fwd:s{stage}",
                              extra={"step": step}):
                    y = fwd(params, x)
                    jax.block_until_ready(y)
                saved.append(x)
                bounded(writer.write, y)

            def bwd_one():
                nonlocal grads, loss_acc
                if last:
                    x, t = saved.popleft()
                    with _tp.span("stage", f"bwd:s{stage}",
                                  extra={"step": step}):
                        loss_m, (dp, dx) = loss_grads(params, x, t)
                        jax.block_until_ready(loss_m)
                    loss_acc += float(loss_m)
                else:
                    cot = bounded(gin.read)
                    x = saved.popleft()
                    with _tp.span("stage", f"bwd:s{stage}",
                                  extra={"step": step}):
                        dp, dx = bwd(params, x, cot)
                        jax.block_until_ready(dp)
                grads = jax.tree_util.tree_map(
                    lambda g, d: g + d, grads, dp)
                if gout is not None:
                    bounded(gout.write, dx)

            for _ in range(W):
                fwd_one()
            for _ in range(M - W):
                fwd_one()
                bwd_one()
            for _ in range(W):
                bwd_one()

            mean_grads = jax.tree_util.tree_map(lambda g: g / M, grads)
            with _tp.span("stage", f"update:s{stage}"):
                if update_fn is not None:
                    params = update_fn(params, mean_grads, step)
                else:
                    params = jax.tree_util.tree_map(
                        lambda p, g: p - lr * g, params, mean_grads)
            if last:
                bounded(loss_w.write,
                        {"step": step, "loss": loss_acc / M})
        return jax.tree_util.tree_map(np.asarray, params)
    finally:
        # the loop's trace context must not outlive it — later tasks
        # on this worker would stamp spans into the pipeline's trace
        _tp.clear_current()
        for ep in (writer, gout, loss_w):
            if ep is not None:
                try:
                    ep.close(timeout=0.5)
                except BaseException:
                    pass
                try:
                    ep.release()
                except BaseException:
                    pass
        for ep in (reader, tgt_reader, gin):
            if ep is not None:
                try:
                    ep.release()
                except BaseException:
                    pass


class MPMDPipeline:
    """Compiled MPMD pipeline over explicit stage actors.

    One actor per stage (each a separate process — in pod mode, rank 0
    of that stage's worker group). `start()` compiles the static stage
    graph: allocates one channel per edge (transport-selected), binds
    wire servers inside the writer processes, and installs the
    persistent stage loops through the ``__rtpu_apply__`` escape hatch
    — the same machinery as ChannelCompiledDAG, specialized to the
    bidirectional stage topology a one-node-per-actor DAG cannot
    express (forward and backward flows share each actor)."""

    def __init__(self, stage_actors: List[Any], stage_params: List[Any],
                 *, stage_fn, loss_fn, consts: tuple = (),
                 num_microbatches: int = 4, schedule: str = "1f1b",
                 steps: int = 1, transport: str = "shm",
                 ring_depth: Optional[int] = None,
                 capacity: int = 4 << 20, update_fn=None,
                 lr: float = 1e-2):
        if len(stage_actors) < 2:
            raise ValueError("an MPMD pipeline needs >= 2 stages")
        if len(stage_actors) != len(stage_params):
            raise ValueError("one params slice per stage actor")
        if schedule not in ("1f1b", "gpipe"):
            raise ValueError("schedule must be 1f1b|gpipe")
        if transport not in ("shm", "wire", "auto"):
            raise ValueError("transport must be shm|wire|auto")
        self._actors = list(stage_actors)
        self._params = list(stage_params)
        self._stage_fn = stage_fn
        self._loss_fn = loss_fn
        self._consts = tuple(consts)
        self._M = int(num_microbatches)
        self._schedule = schedule
        self._steps = int(steps)
        self._transport = transport
        self._depth = _ring_depth(ring_depth)
        self._capacity = int(capacity)
        self._update_fn = update_fn
        self._lr = lr
        self._loop_refs: List[Any] = []
        self._channels: List[Any] = []
        self._abort: Optional[AbortFlag] = None
        self._torn_down = False
        self._watch: Optional[LoopWatchdog] = None
        self._trace_root = 0

    # ------------------------------------------------------ compilation
    def _apply(self, actor, fn, *args):
        from ray_tpu.actor import ActorMethod
        return ActorMethod(actor, "__rtpu_apply__", {}).remote(
            cloudpickle.dumps(fn), *args)

    def start(self) -> None:
        S = len(self._actors)
        transport = self._transport
        if transport == "auto":
            ips = ray_tpu.get(
                [self._apply(a, _host_info) for a in self._actors],
                timeout=60)
            transport = ("shm" if len({*ips, _my_ip()}) <= 1
                         else "wire")
        from ray_tpu._private.specs import SESSION_TAG

        # edge list: (writer, label) — writer None = driver process
        shm = transport == "shm"
        pending: Dict[int, list] = {}    # actor idx -> wire specs

        def make(writer_idx: Optional[int], label: str):
            if shm or writer_idx is None:
                if shm:
                    ch = Channel.create(capacity=self._capacity,
                                        n_readers=1, depth=self._depth,
                                        label=label)
                else:
                    from ray_tpu.experimental.wire_channel import (
                        serve_channel)
                    ch = serve_channel(capacity=self._capacity,
                                       n_readers=1, depth=self._depth,
                                       label=label)
                self._channels.append(ch)
                return ch
            name = f"rtpu_{SESSION_TAG}_wch_{uuid.uuid4().hex[:12]}"
            spec = (name, self._capacity, 1, self._depth, label)
            pending.setdefault(writer_idx, []).append(spec)
            return spec                  # placeholder: resolved below

        data_ch = make(None, "data")
        tgt_ch = make(None, "tgt")
        act = [make(s, f"act{s}") for s in range(S - 1)]
        grad = [make(s + 1, f"grad{s}") for s in range(S - 1)]
        loss_ch = make(S - 1, "loss")

        if pending:
            # one server-binding round trip per stage actor
            refs = {idx: self._apply(self._actors[idx], _serve_many,
                                     specs)
                    for idx, specs in pending.items()}
            resolved: Dict[str, WireChannel] = {}
            for idx, specs in pending.items():
                addrs = ray_tpu.get(refs[idx], timeout=60)
                for spec, addr in zip(specs, addrs):
                    name, cap, nr, depth, label = spec
                    ch = WireChannel(name, cap, nr, depth, addr, label)
                    resolved[name] = ch
                    self._channels.append(ch)

            def fix(ch):
                return resolved[ch[0]] if isinstance(ch, tuple) else ch
            act = [fix(c) for c in act]
            grad = [fix(c) for c in grad]
            loss_ch = fix(loss_ch)

        self._abort = AbortFlag.create()
        self._watch = LoopWatchdog(self._loop_refs, self._abort,
                                   "pipeline stage")
        self._trace_root = _tp.new_id() if _tp.enabled() else 0

        for s, actor in enumerate(self._actors):
            last = s == S - 1
            self._loop_refs.append(self._apply(
                actor, _stage_loop, s, S, self._params[s],
                self._stage_fn, self._loss_fn, self._consts,
                self._schedule, self._M, self._steps,
                data_ch if s == 0 else act[s - 1],     # in_ch
                tgt_ch if last else None,              # tgt_ch
                None if last else act[s],              # out_ch (acts)
                None if last else grad[s],             # gin_ch
                grad[s - 1] if s > 0 else None,        # gout_ch
                loss_ch if last else None,
                self._abort, self._update_fn, self._lr,
                self._trace_root))

        self._data_w = data_ch.writer()
        self._tgt_w = tgt_ch.writer()
        self._loss_r = loss_ch.reader(0)

    def _op(self, op, timeout: Optional[float], what: str):
        """Bounded-slice channel op over the shared dead-stage
        watchdog (dag_channels.LoopWatchdog): a stage dying mid-run
        surfaces HERE instead of hanging the driver, and the abort
        flag unwedges every surviving stage loop."""
        return self._watch.op(op, timeout, what)

    # -------------------------------------------------------- stepping
    def run_step(self, step: int, x, targets,
                 timeout: Optional[float] = 300.0) -> float:
        """Feed one global batch as M microbatches and return the
        step's mean microbatch loss. The driver only streams inputs
        and reads the loss — activations never cross this process."""
        import numpy as np
        x = np.asarray(x)
        targets = np.asarray(targets)
        M = self._M
        if x.shape[0] % M:
            raise ValueError(
                f"batch {x.shape[0]} not divisible into {M} "
                f"microbatches")
        bs = x.shape[0] // M
        with _tp.span("driver", f"pipeline.step:{step}",
                      ctx=(self._trace_root, 0)
                      if self._trace_root else None,
                      root=True):
            for m in range(M):
                mb = np.ascontiguousarray(x[m * bs:(m + 1) * bs])
                tb = np.ascontiguousarray(
                    targets[m * bs:(m + 1) * bs])
                self._op(lambda t, v=mb: self._data_w.write(
                    v, timeout=t), timeout, "feeding microbatch")
                self._op(lambda t, v=tb: self._tgt_w.write(
                    v, timeout=t), timeout, "feeding targets")
            rep = self._op(lambda t: self._loss_r.read(t), timeout,
                           "reading step loss")
        return float(rep["loss"])

    def finish(self, timeout: float = 300.0) -> List[Any]:
        """Collect every stage's final params (numpy pytrees, ragged
        across stages) once all steps have been fed."""
        out = ray_tpu.get(self._loop_refs, timeout=timeout)
        return out

    # -------------------------------------------------------- teardown
    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for w in (getattr(self, "_data_w", None),
                  getattr(self, "_tgt_w", None)):
            if w is not None:
                try:
                    w.close(timeout=0.5)
                except BaseException:
                    pass
        if self._abort is not None:
            try:
                self._abort.set()
            except BaseException:
                pass
        if self._loop_refs:
            try:
                ray_tpu.wait(self._loop_refs,
                             num_returns=len(self._loop_refs),
                             timeout=5.0)
            except BaseException:
                pass
        for w in (getattr(self, "_data_w", None),
                  getattr(self, "_tgt_w", None)):
            if w is not None:
                try:
                    w.release()
                except BaseException:
                    pass
        r = getattr(self, "_loss_r", None)
        if r is not None:
            try:
                r.release()
            except BaseException:
                pass
        for ch in self._channels:
            try:
                ch.destroy()
            except BaseException:
                pass
        if self._abort is not None:
            try:
                self._abort.destroy()
            except BaseException:
                pass

    def __del__(self):
        try:
            self.teardown()
        except BaseException:
            pass


# ---------------------------------------------------------- trainer glue
def fit_pipeline(trainer) -> "Result":
    """JaxTrainer's pipeline_stages= mode: one WorkerGroup per stage,
    layer stack partitioned by the shared helper, MPMDPipeline driving
    the schedule. Returns a normal train Result whose artifacts carry
    the reassembled layer-major params."""
    import numpy as np

    from ray_tpu.train.config import Result
    from ray_tpu.train.worker_group import WorkerGroup

    cfg = trainer._pipeline_config
    S = trainer._pipeline_stages
    if cfg is None:
        raise ValueError(
            "pipeline_stages > 1 requires pipeline_config=")
    for field in ("init_params", "stage_fn", "loss_fn", "batch_fn"):
        if getattr(cfg, field) is None:
            raise ValueError(f"PipelineConfig.{field} is required")

    import jax
    leaves = jax.tree_util.tree_leaves(cfg.init_params)
    parts = partition_layers(leaves[0].shape[0], S)
    stage_params = [slice_stage(cfg.init_params, start, count)
                    for start, count in parts]

    groups = []
    try:
        for s in range(S):
            g = WorkerGroup(cfg.workers_per_stage,
                            trainer._scaling.worker_resources(),
                            trainer._scaling.placement_strategy,
                            name=f"pipeline_stage_{s}")
            g.start()
            groups.append(g)
        # rank 0 of each stage group is that stage's channel endpoint;
        # intra-stage SPMD (workers_per_stage > 1 forming a mesh via
        # jax.distributed) layers on later without changing the
        # channel topology.
        actors = [g.workers[0] for g in groups]
        pipe = MPMDPipeline(
            actors, stage_params, stage_fn=cfg.stage_fn,
            loss_fn=cfg.loss_fn, consts=cfg.consts,
            num_microbatches=cfg.num_microbatches,
            schedule=cfg.schedule, steps=cfg.steps,
            transport=cfg.transport, ring_depth=cfg.ring_depth,
            capacity=cfg.channel_capacity_bytes,
            update_fn=cfg.update_fn, lr=cfg.lr)
        pipe.start()
        history: list = []
        error: Optional[BaseException] = None
        final_params = None
        trace_procs = None
        try:
            for step in range(cfg.steps):
                x, targets = cfg.batch_fn(step)
                loss = pipe.run_step(step, x, targets)
                history.append({"step": step, "loss": loss})
            stage_out = pipe.finish()
            final_params = jax.tree_util.tree_map(
                lambda *xs: np.concatenate(xs, axis=0), *stage_out)
            # collect the cross-process timeline BEFORE the stage
            # workers are killed with their flight recorders — the
            # Result carries what task_timeline() would no longer see
            if _tp.enabled():
                try:
                    from ray_tpu._private import context as _ctx
                    trace_procs = _ctx.get_ctx().state_op(
                        "trace_dump").get("processes", [])
                except Exception:
                    trace_procs = None
        except Exception as e:      # noqa: BLE001
            error = e
        finally:
            pipe.teardown()
        last = dict(history[-1]) if history else {}
        artifacts: Dict[str, Any] = {}
        if final_params is not None:
            artifacts["params"] = final_params
        if trace_procs is not None:
            artifacts["trace_processes"] = trace_procs
            bf = bubble_fraction(trace_procs)
            if bf == bf:               # not NaN
                last["bubble_fraction"] = bf
        return Result(metrics=last, checkpoint=None, path="",
                      metrics_history=history, error=error,
                      artifacts=artifacts or None)
    finally:
        for g in groups:
            g.shutdown()


# ------------------------------------------------------- trace analysis
def _stage_spans(processes, kinds=("stage",), prefixes=("fwd:", "bwd:")):
    for proc in processes:
        off = int(proc.get("offset_ns", 0))
        for ev in proc.get("events", ()):
            _, _, _, kind, name, t0, t1, _ = ev
            if kind in kinds and name.startswith(prefixes):
                yield proc, name, t0 - off, t1 - off


def bubble_fraction(processes, window=None) -> float:
    """Per-stage idle fraction from trace_dump output: for every
    process with stage compute spans, 1 - busy/wall over its own span
    window, averaged across stages. The number ENVELOPE.md's pipeline
    rows report; 1F1B's theoretical floor is (S-1)/(M+S-1). `window`
    (t0_ns, t1_ns on the collector's aligned clock) restricts the
    computation to one measured run — the bench uses it to keep
    earlier runs' spans in the shared rings out of the figure."""
    per_proc = []
    by_proc: Dict[int, list] = {}
    for proc, _, t0, t1 in _stage_spans(processes):
        if window is not None and not (window[0] <= t0
                                       and t1 <= window[1]):
            continue
        by_proc.setdefault(id(proc), []).append((t0, t1))
    for spans in by_proc.values():
        lo = min(t0 for t0, _ in spans)
        hi = max(t1 for _, t1 in spans)
        busy = sum(t1 - t0 for t0, t1 in spans)
        if hi > lo:
            per_proc.append(1.0 - busy / (hi - lo))
    if not per_proc:
        return float("nan")
    return round(sum(per_proc) / len(per_proc), 4)


def overlap_pairs(processes) -> int:
    """Count (transfer span, other-process compute span) pairs that
    overlap in time — the acceptance signal that stage N's channel
    traffic runs CONCURRENTLY with stage N±1's compute instead of
    serializing. Clocks are the collector-aligned offsets trace_dump
    already provides (same-host processes share CLOCK_MONOTONIC)."""
    compute = list(_stage_spans(processes))
    transfers = []
    for proc in processes:
        off = int(proc.get("offset_ns", 0))
        for ev in proc.get("events", ()):
            _, _, _, kind, name, t0, t1, _ = ev
            if kind == "channel" and name.startswith(
                    ("ch.write:", "ch.read:", "ch.wait:")):
                transfers.append((proc, t0 - off, t1 - off))
    count = 0
    for tp_, tt0, tt1 in transfers:
        for cp, _, ct0, ct1 in compute:
            if cp is tp_:
                continue               # different processes only
            if tt0 < ct1 and ct0 < tt1:
                count += 1
                break
    return count
