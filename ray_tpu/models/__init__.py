"""Model zoo: TPU-first transformer families as pure JAX pytrees.

The reference delegates model code to torch/HF; ray_tpu ships its own
flagship decoder (Llama-family, GQA + RoPE + SwiGLU) built directly on
ray_tpu.ops kernels, with parameters as plain pytrees annotated by
logical sharding axes (ray_tpu.parallel.sharding). Layers are stacked
and scanned (`lax.scan`) so compile time is O(1) in depth; remat is a
config switch.
"""
from ray_tpu.models.config import TransformerConfig  # noqa: F401
from ray_tpu.models.decode import (cache_page_bytes,  # noqa: F401
                                   decode_step,
                                   init_paged_cache, prefill)
from ray_tpu.models.transformer import Transformer  # noqa: F401
