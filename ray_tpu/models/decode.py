"""Incremental decoding for the ray_tpu Transformer: paged KV cache.

Serving needs two forwards the training graph never runs: a *prefill*
that processes a whole prompt once while writing every layer's K/V
into cache pages, and a *decode step* that advances a batch of
sequences by one token each against their cached context. Both mirror
`Transformer._layer` exactly (rms_norm / GQA / RoPE / SwiGLU on the
same ops) so prefill+decode logits agree with `Transformer.apply` to
float tolerance — tests pin that equivalence.

The cache is paged (vLLM-style): per layer, `(num_pages, page_size,
kv_heads, head_dim)` arrays, and a sequence owns an arbitrary set of
pages listed in its page table. Paging is what makes continuous
batching viable — a finished sequence returns its pages to the pool
immediately instead of stranding a max-length slab.

Layout note: pages are stacked on a leading layers axis, matching the
stacked/scanned parameter layout. Prefill scans the layer body (one
compile regardless of depth); the decode step unrolls a Python loop
over layers — at serving depths that compile cost is paid once per
(batch, pages) shape and the unrolled body lets XLA alias the per-layer
cache updates in place.

Out-of-range page writes use `num_pages` as the drop sentinel: scatter
mode="drop" discards them, which is how padded prefill tails and
inactive decode rows stay out of the cache without branching.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.attention import flash_attention
from ray_tpu.ops.norms import rms_norm
from ray_tpu.ops.rope import apply_rope_cached, rope_cos_sin

Params = Dict[str, Any]
KVCache = Dict[str, jax.Array]


def init_paged_cache(config, num_pages: int, page_size: int,
                     dtype=None) -> KVCache:
    """Zeroed paged cache: k/v each (layers, pages, page, kv, hd)."""
    if config.moe_num_experts:
        raise NotImplementedError(
            "paged decoding supports dense FFN layers only")
    dt = dtype or config.activation_dtype
    shape = (config.n_layers, num_pages, page_size,
             config.kv_heads, config.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def cache_page_bytes(config, page_size: int, tp_shards: int = 1,
                     dtype=None) -> int:
    """Bytes one page costs per shard (k+v, all layers). The engine
    sizes its pool off this: kv heads split across tp shards, so a
    bigger mesh affords more pages for the same per-chip budget."""
    dt = jnp.dtype(dtype or config.activation_dtype)
    kv_local = max(1, config.kv_heads // max(1, tp_shards))
    return (2 * config.n_layers * page_size * kv_local
            * config.head_dim * dt.itemsize)


def _qkv(config, layer: Params, h):
    ad = config.activation_dtype
    b, s, _ = h.shape
    hd = config.head_dim
    q = (h @ layer["wq"].astype(ad)).reshape(b, s, config.n_heads, hd)
    k = (h @ layer["wk"].astype(ad)).reshape(b, s, config.kv_heads, hd)
    v = (h @ layer["wv"].astype(ad)).reshape(b, s, config.kv_heads, hd)
    return q, k, v


def _mlp(config, layer: Params, x):
    ad = config.activation_dtype
    h = rms_norm(x, layer["mlp_norm"], config.norm_eps)
    gate = jax.nn.silu(h @ layer["gate"].astype(ad))
    up = h @ layer["up"].astype(ad)
    return x + (gate * up) @ layer["down"].astype(ad)


def prefill(model, params: Params, tokens: jax.Array, true_len,
            page_table: jax.Array, cache: KVCache,
            page_size: int) -> Tuple[jax.Array, KVCache]:
    """Process one padded prompt, writing K/V into the cache pages.

    tokens: (s_pad,) int32, garbage past true_len (the causal mask
    keeps the tail from contaminating positions < true_len).
    true_len: scalar int32, actual prompt length.
    page_table: (max_pages,) int32 page ids; entries past the prompt's
    pages may be anything (writes there are dropped).

    Returns (last-position logits (vocab,) f32, updated cache).
    """
    c = model.config
    ad = c.activation_dtype
    num_pages = cache["k"].shape[1]
    s = tokens.shape[0]
    toks = tokens[None]                                   # (1, s)
    positions = jnp.arange(s)[None]
    x = model._embed_lookup(params["embed"].astype(ad), toks)
    rope = rope_cos_sin(positions, c.head_dim, c.rope_theta)
    cos, sin = rope

    def body(x, layer):
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k, v = _qkv(c, layer, h)
        q = apply_rope_cached(q, cos, sin)
        k = apply_rope_cached(k, cos, sin)
        qt, kt, vt = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
        attn = flash_attention(qt, kt, vt, causal=True,
                               block_q=c.attn_block_q,
                               block_k=c.attn_block_k)
        attn = attn.transpose(0, 2, 1, 3).reshape(
            1, s, c.n_heads * c.head_dim)
        x = x + attn @ layer["wo"].astype(ad)
        x = _mlp(c, layer, x)
        return x, (k[0], v[0])                     # (s, kv, hd) each

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], c.norm_eps)
    last = jnp.take(x[0], true_len - 1, axis=0)
    logits = (last @ model._head(params).astype(ad)).astype(jnp.float32)

    pos = jnp.arange(s)
    page_ids = jnp.take(page_table, pos // page_size, mode="clip")
    # positions past the prompt scatter to the drop sentinel
    page_ids = jnp.where(pos < true_len, page_ids, num_pages)
    slots = pos % page_size
    cache = {
        "k": cache["k"].at[:, page_ids, slots].set(
            ks.astype(cache["k"].dtype), mode="drop"),
        "v": cache["v"].at[:, page_ids, slots].set(
            vs.astype(cache["v"].dtype), mode="drop"),
    }
    return logits, cache


def decode_step(model, params: Params, cache: KVCache,
                tokens: jax.Array, positions: jax.Array,
                page_tables: jax.Array, active: jax.Array,
                page_size: int) -> Tuple[jax.Array, KVCache]:
    """Advance a padded batch by one token each.

    tokens: (B,) int32 current input token per row.
    positions: (B,) int32 absolute position the token occupies.
    page_tables: (B, max_pages) int32, -1 for unassigned slots.
    active: (B,) bool — inactive (padding) rows neither write cache
    nor produce meaningful logits.

    Returns (logits (B, vocab) f32, updated cache).
    """
    c = model.config
    ad = c.activation_dtype
    hd = c.head_dim
    ck, cv = cache["k"], cache["v"]
    num_pages = ck.shape[1]
    B = tokens.shape[0]
    max_pages = page_tables.shape[1]
    span = max_pages * page_size

    x = model._embed_lookup(params["embed"].astype(ad),
                            tokens[:, None])               # (B, 1, e)
    cos, sin = rope_cos_sin(positions[:, None], hd, c.rope_theta)

    my_page = jnp.take_along_axis(
        page_tables, (positions // page_size)[:, None], axis=1)[:, 0]
    wr_page = jnp.where(active & (my_page >= 0), my_page, num_pages)
    wr_slot = positions % page_size
    # context mask: cache slot j is visible iff j <= position and its
    # page is assigned (own-position k/v is written before the read)
    flat = jnp.arange(span)
    assigned = jnp.repeat(page_tables >= 0, page_size, axis=1)
    mask = (flat[None, :] <= positions[:, None]) & assigned
    gather_pt = jnp.clip(page_tables, 0, num_pages - 1)
    groups = c.n_heads // c.kv_heads
    scale = 1.0 / (hd ** 0.5)

    layers = params["layers"]
    for i in range(c.n_layers):
        layer = jax.tree_util.tree_map(lambda a: a[i], layers)
        h = rms_norm(x, layer["attn_norm"], c.norm_eps)
        q, k, v = _qkv(c, layer, h)                  # (B, 1, heads, hd)
        q = apply_rope_cached(q, cos, sin)
        k = apply_rope_cached(k, cos, sin)
        ck = ck.at[i, wr_page, wr_slot].set(
            k[:, 0].astype(ck.dtype), mode="drop")
        cv = cv.at[i, wr_page, wr_slot].set(
            v[:, 0].astype(cv.dtype), mode="drop")
        keys = ck[i][gather_pt].reshape(B, span, c.kv_heads, hd)
        vals = cv[i][gather_pt].reshape(B, span, c.kv_heads, hd)
        qg = q[:, 0].reshape(B, c.kv_heads, groups, hd)
        scores = jnp.einsum(
            "bkgd,bskd->bkgs", qg.astype(jnp.float32),
            keys.astype(jnp.float32)) * scale
        scores = jnp.where(mask[:, None, None, :], scores,
                           jnp.finfo(jnp.float32).min)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bkgs,bskd->bkgd", probs,
                         vals.astype(jnp.float32)).astype(ad)
        out = out.reshape(B, 1, c.n_heads * hd)
        x = x + out @ layer["wo"].astype(ad)
        x = _mlp(c, layer, x)

    x = rms_norm(x, params["final_norm"], c.norm_eps)
    logits = (x[:, 0] @ model._head(params).astype(ad))
    return logits.astype(jnp.float32), {"k": ck, "v": cv}
