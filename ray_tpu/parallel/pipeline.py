"""GPipe pipeline parallelism over the `pp` mesh axis.

The reference builds pipeline schedules out of compiled actor DAGs with
NCCL p2p channels (reference python/ray/dag/dag_node_operation.py,
experimental/channel/torch_tensor_nccl_channel.py). The TPU-native
equivalent is a SPMD microbatch schedule INSIDE one XLA program:
`jax.shard_map` manual over ONLY the pp axis (other mesh axes — dp,
fsdp, tp, sp — stay auto, so pipeline composes with GSPMD sharding),
with `lax.ppermute` rotating activations stage→stage over ICI/DCN.

Schedule: classic GPipe fill-drain. With S stages and M microbatches
the loop runs M+S-1 ticks; stage 0 injects microbatch t at tick t, the
last stage emits microbatch t-(S-1). Bubble fraction (S-1)/(M+S-1)
shrinks as M grows — choose M ≥ 4·S for <20% bubble (config knob
`pipeline_microbatches`).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def split_stages(layer_params: Any, n_stages: int) -> Any:
    """Reshape layer-stacked leaves (L, ...) -> (S, L//S, ...)."""
    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(
                f"{L} layers not divisible into {n_stages} pipeline "
                f"stages")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])
    return jax.tree_util.tree_map(reshape, layer_params)


def pipeline_apply(mesh: Mesh,
                   stage_fn: Callable[..., jax.Array],
                   layer_params: Any,
                   x: jax.Array,
                   num_microbatches: int,
                   consts: tuple = ()) -> jax.Array:
    """Run `stage_fn(stage_params, x_microbatch, *consts)` (one stage's
    layer stack applied to one microbatch) over the pp axis with a
    GPipe schedule.

    x: (batch, ...) activations; `consts` are stage-invariant arrays
    (e.g. rope caches) passed explicitly — closures over tracers don't
    cross the shard_map boundary. Returns x's shape, replicated over pp
    (downstream ops run outside the manual region).

    NOTE: call this under an outer jit (the normal train step). The
    inner jit below exists so EAGER callers work at all (partial-manual
    shard_map only lowers under jit), but eager callers re-trace per
    call — fine for debugging, wrong for a training loop.
    """
    n_stages = mesh.shape["pp"]
    if n_stages <= 1:
        raise ValueError("pipeline_apply needs a pp axis > 1")
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible into {M} microbatches")
    micro = x.reshape(M, b // M, *x.shape[1:])
    stacked = split_stages(layer_params, n_stages)

    @functools.partial(
        jax.shard_map, mesh=mesh, axis_names={"pp"},
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                  P(), jax.tree_util.tree_map(lambda _: P(),
                                              tuple(consts))),
        out_specs=P(), check_vma=False)
    def run(stacked_local, micro_local, consts_local):
        params_local = jax.tree_util.tree_map(lambda p: p[0],
                                              stacked_local)
        stage = lax.axis_index("pp")
        state = jnp.zeros_like(micro_local[0])
        outputs = jnp.zeros_like(micro_local)
        ticks = M + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (clamped; the tail ticks feed
            # it stale data whose results never reach an emit slot)
            inject = lax.dynamic_index_in_dim(
                micro_local, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(stage == 0, inject, state)
            y = stage_fn(params_local, x_in, *consts_local)
            # last stage emits microbatch t-(S-1) once the fill ends
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            emit = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, y, cur), out_idx, 0)
            # rotate activations to the next stage
            state = lax.ppermute(y, "pp", perm)
            return state, outputs

        _, outputs = lax.fori_loop(0, ticks, tick, (state, outputs))
        # broadcast the last stage's outputs to every pp shard (sum of
        # one non-zero contribution)
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pp")
        return outputs

    # partial-manual shard_map only lowers under jit; wrapping here keeps
    # eager callers (model.loss outside jit) working — jit-in-jit is a
    # no-op when the caller already traces.
    out = jax.jit(run)(stacked, micro, tuple(consts))
    return out.reshape(b, *x.shape[1:])
