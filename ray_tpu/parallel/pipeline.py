"""GPipe pipeline parallelism over the `pp` mesh axis.

The reference builds pipeline schedules out of compiled actor DAGs with
NCCL p2p channels (reference python/ray/dag/dag_node_operation.py,
experimental/channel/torch_tensor_nccl_channel.py). The TPU-native
equivalent is a SPMD microbatch schedule INSIDE one XLA program:
`jax.shard_map` manual over ONLY the pp axis (other mesh axes — dp,
fsdp, tp, sp — stay auto, so pipeline composes with GSPMD sharding),
with `lax.ppermute` rotating activations stage→stage over ICI/DCN.

Schedule: classic GPipe fill-drain. With S stages and M microbatches
the loop runs M+S-1 ticks; stage 0 injects microbatch t at tick t, the
last stage emits microbatch t-(S-1). Bubble fraction (S-1)/(M+S-1)
shrinks as M grows — choose M ≥ 4·S for <20% bubble (config knob
`pipeline_microbatches`).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def _shard_map(f, *, mesh, axis_names, in_specs, out_specs,
               check_vma=False):
    """jax.shard_map compat: the stable partial-manual API when this
    jax has it, else jax.experimental.shard_map (axis_names -> its
    `auto` complement, check_vma -> check_rep). Keeps the pipeline
    schedules runnable across the jax versions the fleet actually
    ships."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, axis_names=axis_names,
                             in_specs=in_specs, out_specs=out_specs,
                             check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    auto = frozenset(mesh.axis_names) - set(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, auto=auto)


def partition_layers(n_layers: int, n_stages: int) -> list:
    """Canonical stage partition: ``[(start, count), ...]`` per stage,
    with the remainder layers assigned to the LAST stage (it already
    sits next to the loss, so in MPMD mode its extra work overlaps the
    other stages' cooldown bubble). Shared by the SPMD schedules here
    (uneven splits via ``layer_fn``) and by the MPMD stage assignment
    (train/pipeline.py), so the two parallelism modes can never
    disagree about which stage owns which layer."""
    if n_stages < 1:
        raise ValueError(f"need at least one stage, got {n_stages}")
    if n_layers < n_stages:
        raise ValueError(
            f"{n_layers} layers cannot fill {n_stages} pipeline stages")
    k, r = divmod(n_layers, n_stages)
    parts = [(s * k, k) for s in range(n_stages - 1)]
    parts.append(((n_stages - 1) * k, k + r))
    return parts


def slice_stage(layer_params: Any, start: int, count: int) -> Any:
    """One stage's sub-stack: leaves (L, ...) -> (count, ...). The MPMD
    counterpart of split_stages — per-stage pytrees may be RAGGED
    across stages (each stage is its own program), which is exactly why
    uneven splits are free in MPMD mode."""
    return jax.tree_util.tree_map(
        lambda p: p[start:start + count], layer_params)


def split_stages(layer_params: Any, n_stages: int) -> Any:
    """Reshape layer-stacked leaves (L, ...) -> (S, L//S, ...)."""
    def reshape(p):
        L = p.shape[0]
        if L % n_stages:
            raise ValueError(
                f"{L} layers not divisible into {n_stages} pipeline "
                f"stages; pass layer_fn= for an uneven split "
                f"(remainder layers go to the last stage, see "
                f"partition_layers) or use the MPMD pipeline "
                f"(train/pipeline.py), where ragged stages are free")
        return p.reshape(n_stages, L // n_stages, *p.shape[1:])
    return jax.tree_util.tree_map(reshape, layer_params)


def split_stages_padded(layer_params: Any, n_stages: int):
    """Uneven-split stacking for the SPMD schedules: leaves (L, ...)
    -> (S, kmax, ...) zero-padded per stage, plus the per-stage valid
    counts. Padded slots are masked to IDENTITY inside the per-layer
    scan (`_make_stage_call`), so every shard runs the same program
    shape while stages apply different layer counts."""
    leaves = jax.tree_util.tree_leaves(layer_params)
    if not leaves:
        raise ValueError("layer_params has no leaves")
    L = leaves[0].shape[0]
    parts = partition_layers(L, n_stages)
    kmax = max(c for _, c in parts)

    def stack(p):
        rows = []
        for start, count in parts:
            block = p[start:start + count]
            if count < kmax:
                pad = jnp.zeros((kmax - count,) + p.shape[1:], p.dtype)
                block = jnp.concatenate([block, pad], axis=0)
            rows.append(block)
        return jnp.stack(rows)
    import numpy as np
    return (jax.tree_util.tree_map(stack, layer_params),
            np.asarray([c for _, c in parts], dtype=np.int32))


def _unpad_stage_axis(stacked: Any, layer_params: Any,
                      n_stages: int) -> Any:
    """Inverse of split_stages_padded along the layer axis: (S, kmax,
    ...) -> (L, ...) dropping the padded rows (used to return grads in
    the caller's layer-major layout)."""
    leaves = jax.tree_util.tree_leaves(layer_params)
    parts = partition_layers(leaves[0].shape[0], n_stages)
    return jax.tree_util.tree_map(
        lambda g: jnp.concatenate(
            [g[s, :count] for s, (_, count) in enumerate(parts)],
            axis=0),
        stacked)


def _make_stage_call(stage_fn, layer_fn, counts):
    """Uniform per-stage apply: ``call(params, x, stage, consts)``.

    stage_fn mode (even splits): the caller's whole-sub-stack function,
    unchanged. layer_fn mode (uneven splits): a masked per-layer scan —
    ``layer_fn(one_layer_params, x, *consts) -> x`` is applied to every
    padded slot, and slots past this stage's valid count pass the
    activation through unchanged (`where` keeps the program shape
    identical across shards; grads through padded slots are exactly
    zero because the output disconnects from them)."""
    if layer_fn is None:
        if stage_fn is None:
            raise ValueError("pass stage_fn or layer_fn")
        return lambda p, x, stage, consts: stage_fn(p, x, *consts)
    counts = jnp.asarray(counts, jnp.int32)

    def call(p, x, stage, consts):
        n_valid = counts[stage]

        def body(carry, layer):
            i, xx = carry
            y = layer_fn(layer, xx, *consts)
            return (i + 1, jnp.where(i < n_valid, y, xx)), None
        (_, out), _ = lax.scan(body, (jnp.int32(0), x), p)
        return out
    return call


def _stack_for(mesh_stages: int, layer_params: Any, layer_fn):
    """(stacked pytree, stage_call counts) for either calling mode."""
    if layer_fn is None:
        return split_stages(layer_params, mesh_stages), None
    return split_stages_padded(layer_params, mesh_stages)


def pipeline_apply(mesh: Mesh,
                   stage_fn: Callable[..., jax.Array],
                   layer_params: Any,
                   x: jax.Array,
                   num_microbatches: int,
                   consts: tuple = (),
                   layer_fn: Callable[..., jax.Array] = None) -> jax.Array:
    """Run `stage_fn(stage_params, x_microbatch, *consts)` (one stage's
    layer stack applied to one microbatch) over the pp axis with a
    GPipe schedule.

    x: (batch, ...) activations; `consts` are stage-invariant arrays
    (e.g. rope caches) passed explicitly — closures over tracers don't
    cross the shard_map boundary. Returns x's shape, replicated over pp
    (downstream ops run outside the manual region).

    Uneven layer counts: pass ``layer_fn(one_layer_params, x, *consts)
    -> x`` INSTEAD of stage_fn. The stack is padded to ceil(L/S) per
    stage (remainder layers on the last stage, `partition_layers`) and
    a masked per-layer scan keeps padded slots identity, so L need not
    divide the stage count.

    NOTE: call this under an outer jit (the normal train step). The
    inner jit below exists so EAGER callers work at all (partial-manual
    shard_map only lowers under jit), but eager callers re-trace per
    call — fine for debugging, wrong for a training loop.
    """
    n_stages = mesh.shape["pp"]
    if n_stages <= 1:
        raise ValueError("pipeline_apply needs a pp axis > 1")
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible into {M} microbatches")
    micro = x.reshape(M, b // M, *x.shape[1:])
    stacked, counts = _stack_for(n_stages, layer_params, layer_fn)
    stage_call = _make_stage_call(stage_fn, layer_fn, counts)

    @functools.partial(
        _shard_map, mesh=mesh, axis_names={"pp"},
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                  P(), jax.tree_util.tree_map(lambda _: P(),
                                              tuple(consts))),
        out_specs=P(), check_vma=False)
    def run(stacked_local, micro_local, consts_local):
        params_local = jax.tree_util.tree_map(lambda p: p[0],
                                              stacked_local)
        stage = lax.axis_index("pp")
        state = jnp.zeros_like(micro_local[0])
        outputs = jnp.zeros_like(micro_local)
        ticks = M + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 injects microbatch t (clamped; the tail ticks feed
            # it stale data whose results never reach an emit slot)
            inject = lax.dynamic_index_in_dim(
                micro_local, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(stage == 0, inject, state)
            y = stage_call(params_local, x_in, stage, consts_local)
            # last stage emits microbatch t-(S-1) once the fill ends
            out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            emit = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(emit, y, cur), out_idx, 0)
            # rotate activations to the next stage
            state = lax.ppermute(y, "pp", perm)
            return state, outputs

        _, outputs = lax.fori_loop(0, ticks, tick, (state, outputs))
        # broadcast the last stage's outputs to every pp shard (sum of
        # one non-zero contribution)
        outputs = lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), "pp")
        return outputs

    # partial-manual shard_map only lowers under jit; wrapping here keeps
    # eager callers (model.loss outside jit) working — jit-in-jit is a
    # no-op when the caller already traces.
    out = jax.jit(run)(stacked, micro, tuple(consts))
    return out.reshape(b, *x.shape[1:])


def pipeline_grads_1f1b(mesh: Mesh,
                        stage_fn: Callable[..., jax.Array],
                        loss_fn: Callable[[jax.Array, jax.Array],
                                          jax.Array],
                        layer_params: Any,
                        x: jax.Array,
                        targets: jax.Array,
                        num_microbatches: int,
                        consts: tuple = (),
                        layer_fn: Callable[..., jax.Array] = None):
    """One-forward-one-backward pipeline schedule (the reference's
    dag_node_operation.py builds exactly this ordering for its NCCL
    actor pipelines; Narayanan et al. PipeDream-Flush / Megatron-LM).

    Unlike GPipe-then-autodiff — which must keep ALL M microbatch
    activations live until the loss — the backward of microbatch m
    starts as soon as its forward leaves the last stage, so each stage
    stores at most 2(S-1)+1 stage-input activations (a static ring XLA
    allocates ONCE) independent of M; stage backwards recompute their
    forward from the saved input (remat), the standard trade.

    Per global tick t (clock-driven SPMD emulation, T = M + 2(S-1)
    ticks), stage s runs the forward of microbatch t-s and the backward
    of microbatch t-2(S-1)+s when those indices are in range; the last
    stage computes the per-microbatch loss + output cotangent in the
    same tick its forward completes, activations ppermute up the pp
    ring while cotangents ppermute down.

    Returns (mean loss over all microbatches, grads in the layer-major
    (L, ...) layout of `layer_params`). stage_fn/loss_fn as in
    pipeline_apply, with loss_fn(y_microbatch, target_microbatch) ->
    scalar summed loss for that microbatch. Uneven layer counts: pass
    ``layer_fn`` instead of stage_fn (see pipeline_apply) — grads come
    back unpadded in the caller's (L, ...) layout either way.
    """
    n_stages = mesh.shape["pp"]
    if n_stages <= 1:
        raise ValueError("pipeline_grads_1f1b needs a pp axis > 1")
    S = n_stages
    M = num_microbatches
    b = x.shape[0]
    if b % M:
        raise ValueError(f"batch {b} not divisible into {M} microbatches")
    micro = x.reshape(M, b // M, *x.shape[1:])
    tmicro = targets.reshape(M, b // M, *targets.shape[1:])
    stacked, counts = _stack_for(n_stages, layer_params, layer_fn)
    stage_call = _make_stage_call(stage_fn, layer_fn, counts)
    A = min(M, 2 * (S - 1) + 1)       # activation ring slots per stage

    @functools.partial(
        _shard_map, mesh=mesh, axis_names={"pp"},
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked),
                  P(), P(),
                  jax.tree_util.tree_map(lambda _: P(), tuple(consts))),
        out_specs=(P(),
                   jax.tree_util.tree_map(lambda _: P("pp"), stacked)),
        check_vma=False)
    def run(stacked_local, micro_local, tmicro_local, consts_local):
        params_local = jax.tree_util.tree_map(lambda p: p[0],
                                              stacked_local)
        stage = lax.axis_index("pp")
        last = S - 1
        up = [(i, (i + 1) % S) for i in range(S)]
        down = [(i, (i - 1) % S) for i in range(S)]

        def fwd_only(p, xx):
            return stage_call(p, xx, stage, consts_local)

        zero_act = jnp.zeros_like(micro_local[0])
        ring0 = jnp.zeros((A,) + zero_act.shape, zero_act.dtype)
        grads0 = jax.tree_util.tree_map(jnp.zeros_like, params_local)
        T = M + 2 * (S - 1)

        def tick(t, carry):
            fwd_carry, bwd_carry, ring, grads, loss_acc = carry
            # ---------- forward half-tick
            m_f = t - stage
            do_fwd = jnp.logical_and(m_f >= 0, m_f < M)
            m_f_c = jnp.clip(m_f, 0, M - 1)
            inject = lax.dynamic_index_in_dim(micro_local, m_f_c, 0,
                                              keepdims=False)
            x_in = jnp.where(stage == 0, inject, fwd_carry)
            y = fwd_only(params_local, x_in)
            ring = lax.dynamic_update_index_in_dim(
                ring, jnp.where(do_fwd, x_in, ring[m_f_c % A]),
                m_f_c % A, 0)
            # last stage: per-microbatch loss + output cotangent NOW
            tgt = lax.dynamic_index_in_dim(tmicro_local, m_f_c, 0,
                                           keepdims=False)
            loss_m, dLdy = jax.value_and_grad(loss_fn)(y, tgt)
            take_loss = jnp.logical_and(stage == last, do_fwd)
            loss_acc = loss_acc + jnp.where(take_loss, loss_m, 0.0)
            # ---------- backward half-tick
            m_b = t - 2 * (S - 1) + stage
            do_bwd = jnp.logical_and(m_b >= 0, m_b < M)
            m_b_c = jnp.clip(m_b, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(ring, m_b_c % A, 0,
                                               keepdims=False)
            # last stage consumes its own fresh cotangent (its bwd of m
            # shares the tick with its fwd of m); others take the grad
            # arriving from the next stage
            cot = jnp.where(stage == last, dLdy, bwd_carry)
            _, vjp = jax.vjp(fwd_only, params_local, x_saved)
            dparams, dx = vjp(cot)
            grads = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(do_bwd, d, 0.0), grads,
                dparams)
            # ---------- communication
            fwd_carry = lax.ppermute(y, "pp", up)
            bwd_carry = lax.ppermute(jnp.where(do_bwd, dx,
                                               jnp.zeros_like(dx)),
                                     "pp", down)
            return fwd_carry, bwd_carry, ring, grads, loss_acc

        _, _, _, grads, loss_acc = lax.fori_loop(
            0, T, tick, (zero_act, zero_act, ring0, grads0,
                         jnp.zeros((), x.dtype)))
        # total loss lives on the last stage only; returned loss is the
        # microbatch mean, so grads scale by 1/M to match d(loss)/dp
        loss = lax.psum(jnp.where(stage == last, loss_acc, 0.0), "pp")
        grads = jax.tree_util.tree_map(lambda g: g[None] / M, grads)
        return loss / M, grads

    loss, stacked_grads = jax.jit(run)(stacked, micro, tmicro,
                                       tuple(consts))
    if layer_fn is None:
        grads = jax.tree_util.tree_map(
            lambda g, p: g.reshape(p.shape), stacked_grads, layer_params)
    else:
        grads = _unpad_stage_axis(stacked_grads, layer_params, S)
    return loss, grads
