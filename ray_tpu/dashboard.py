"""Dashboard: HTTP observability endpoints + minimal HTML view.

Parity (shape): reference dashboard head (dashboard/head.py:61) with
its per-entity modules — reduced to a driver-thread HTTP server over
the state API + metrics registry. Endpoints:

  GET /api/nodes /api/actors /api/tasks /api/placement_groups
  GET /api/cluster      (total/available resources + object store)
  GET /api/task_summary /api/actor_summary
  GET /api/jobs         (submitted jobs, reference modules/job)
  GET /api/logs         (available job log files)
  GET /api/logs/<job>   (tail of one job's log; ?lines=N)
  GET /metrics          (Prometheus exposition of util.metrics)
  GET /                 (HTML tables auto-refreshing off the JSON API)
"""
from __future__ import annotations

import json
import threading
from typing import Optional

_SERVER = None

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:monospace;margin:1.5em;background:#111;color:#ddd}
h2{color:#7ac}table{border-collapse:collapse;margin-bottom:1.5em}
td,th{border:1px solid #444;padding:3px 9px;text-align:left}
th{background:#223}</style></head><body>
<h1>ray_tpu</h1>
<div id="out">loading…</div>
<script>
const SECTIONS = ["cluster","nodes","actors","task_summary",
                  "placement_groups"];
function table(rows){
  if(!Array.isArray(rows)) rows=[rows];
  if(!rows.length) return "<i>none</i>";
  const keys=Object.keys(rows[0]);
  return "<table><tr>"+keys.map(k=>`<th>${k}</th>`).join("")+"</tr>"+
    rows.map(r=>"<tr>"+keys.map(k=>
      `<td>${JSON.stringify(r[k])}</td>`).join("")+"</tr>").join("")+
    "</table>";
}
async function refresh(){
  let html="";
  for(const s of SECTIONS){
    const r=await fetch("/api/"+s); const data=await r.json();
    html+=`<h2>${s}</h2>`+table(data);
  }
  document.getElementById("out").innerHTML=html;
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> int:
    """Serve the dashboard from the driver; returns the bound port."""
    global _SERVER
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ray_tpu.util import state as state_api
    from ray_tpu.util.metrics import DEFAULT_REGISTRY

    def api(path: str):
        from urllib.parse import parse_qs, urlsplit
        url = urlsplit(path)
        path, query = url.path, parse_qs(url.query)
        if path.startswith("logs"):
            from ray_tpu.job_submission import default_client
            client = default_client()
            parts = path.split("/", 1)
            if len(parts) == 1 or not parts[1]:
                return client.list_log_files()
            lines = int(query.get("lines", ["200"])[0])
            return {"job_id": parts[1],
                    "lines": client.tail_logs(parts[1], lines)}
        if path == "jobs":
            import dataclasses as _dc

            from ray_tpu.job_submission import default_client
            return [_dc.asdict(j) for j in
                    default_client().list_jobs()]
        if path == "actor_summary":
            return state_api.summarize_actors()
        if path == "nodes":
            return state_api.list_nodes()
        if path == "actors":
            return state_api.list_actors()
        if path == "tasks":
            return state_api.list_tasks()
        if path == "task_summary":
            return state_api.summarize_tasks()
        if path == "placement_groups":
            return state_api.list_placement_groups()
        if path == "cluster":
            return {"total": state_api.cluster_resources(),
                    "available": state_api.available_resources(),
                    "object_store": state_api.object_store_stats()}
        raise KeyError(path)

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                if self.path == "/" or self.path == "/index.html":
                    body = _INDEX_HTML.encode()
                    ctype = "text/html"
                elif self.path == "/metrics":
                    body = DEFAULT_REGISTRY.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/api/"):
                    body = json.dumps(api(self.path[5:]),
                                      default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
            except BaseException as e:  # noqa: BLE001
                body = json.dumps({"error": repr(e)}).encode()
                ctype = "application/json"
                self.send_response(500)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    _SERVER = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_SERVER.serve_forever, daemon=True).start()
    return _SERVER.server_address[1]


def stop_dashboard() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER = None
