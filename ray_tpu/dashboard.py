"""Dashboard: HTTP observability endpoints + minimal HTML view.

Parity (shape): reference dashboard head (dashboard/head.py:61) with
its per-entity modules — reduced to a driver-thread HTTP server over
the state API + metrics registry. Endpoints:

  GET /api/nodes /api/actors /api/tasks /api/placement_groups
  GET /api/cluster      (total/available resources + object store)
  GET /api/task_summary /api/actor_summary
  GET /api/jobs         (submitted jobs, reference modules/job)
  GET /api/logs         (available job log files)
  GET /api/logs/<job>   (tail of one job's log; ?lines=N)
  GET /api/serve_applications  (serve apps -> deployments/replicas)
  GET /api/timeline     (Chrome-trace JSON of recorded task events —
                         load in Perfetto / chrome://tracing)
  GET /api/metrics_summary  (cluster metrics JSON: windowed task-
                         latency percentiles + sparkline ring, r11)
  GET /metrics          (Prometheus exposition — CLUSTER-aggregated
                         when a runtime is attached: every process's
                         registry merged with node/worker labels;
                         head-local util.metrics otherwise)
  GET /                 (single-page frontend app: tabbed views over
                         the JSON API with utilization + host-stats
                         bars, auto-refreshing; no external assets)
"""
from __future__ import annotations

import json
import threading
from typing import Optional

_SERVER = None

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:ui-monospace,Menlo,monospace;margin:0;background:#0e1116;
 color:#d6dbe3}
header{display:flex;align-items:baseline;gap:1.2em;padding:.7em 1.2em;
 background:#151a22;border-bottom:1px solid #2a3240}
h1{font-size:1.1em;margin:0;color:#8ab4f8}
#age{color:#6b7686;font-size:.8em}
nav{display:flex;gap:.2em;padding:.4em 1em;background:#11151c}
nav button{background:none;border:0;color:#9aa5b5;font:inherit;
 padding:.35em .8em;cursor:pointer;border-radius:4px}
nav button.on{background:#223049;color:#cfe1ff}
main{padding:1em 1.2em}
h2{color:#8ab4f8;font-size:.95em;margin:1.2em 0 .4em}
table{border-collapse:collapse;margin-bottom:1em;font-size:.85em}
td,th{border:1px solid #2a3240;padding:3px 9px;text-align:left}
th{background:#1a2230;color:#aebdd4}
tr:nth-child(even) td{background:#121823}
.bar{display:inline-block;width:120px;height:9px;background:#222b3a;
 border-radius:4px;vertical-align:middle;margin-right:.5em}
.bar i{display:block;height:100%;border-radius:4px;background:#4f8ef7}
.bar i.hot{background:#e2734b}
.kpis{display:flex;gap:1em;flex-wrap:wrap;margin:.6em 0}
.kpi{background:#151c28;border:1px solid #283142;border-radius:6px;
 padding:.6em 1em;min-width:9em}
.kpi b{display:block;font-size:1.3em;color:#e8eef7}
.kpi span{color:#8a96a8;font-size:.75em}
a{color:#8ab4f8}
i.none{color:#5a6474}
</style></head><body>
<header><h1>ray_tpu</h1><span id="age"></span>
<span style="flex:1"></span>
<a href="/api/timeline" download="timeline.json">timeline</a>
<a href="/metrics">metrics</a></header>
<nav id="nav"></nav><main id="out">loading…</main>
<script>
const TABS={Overview:ovw,Nodes:nodes,Workers:workers,Actors:actors,
            Tasks:tasks,Metrics:metricsTab,Serve:serveApps,Jobs:jobs,
            "Placement Groups":pgs};
let cur="Overview", cache={};
async function J(p){const r=await fetch("/api/"+p);return r.json()}
function esc(x){return String(x).replace(/&/g,"&amp;").replace(/</g,"&lt;")}
function cell(v){return typeof v==="object"&&v!==null?
  esc(JSON.stringify(v)):esc(v)}
function table(rows,keys){
  if(!Array.isArray(rows)) rows=[rows];
  if(!rows.length) return "<i class=none>none</i>";
  keys=keys||Object.keys(rows[0]);
  return "<table><tr>"+keys.map(k=>`<th>${esc(k)}</th>`).join("")+"</tr>"+
    rows.map(r=>"<tr>"+keys.map(k=>`<td>${cell(r[k]??"")}</td>`)
      .join("")+"</tr>").join("")+"</table>";
}
function bar(frac,label){
  const pct=Math.min(100,Math.round(100*frac));
  return `<span class=bar><i class="${pct>85?"hot":""}"
    style="width:${pct}%"></i></span>${label??pct+"%"}`;
}
function kpi(v,l){return `<div class=kpi><b>${v}</b><span>${l}</span></div>`}
async function ovw(){
  const c=await J("cluster"),u=await J("usage");
  let h="<div class=kpis>";
  h+=kpi(u.nodes_alive,"alive nodes"+(u.nodes_dead?
        ` (+${u.nodes_dead} dead)`:""));
  h+=kpi(u.workers,"workers");
  h+=kpi(Object.values(u.actors).reduce((a,b)=>a+b,0)||0,"actors");
  h+=kpi(Object.entries(u.tasks).map(([k,v])=>`${k}:${v}`).join(" ")
         ||"0","task states");
  h+=kpi((c.object_store.bytes/1048576).toFixed(1)+" MB","object store");
  h+=kpi((u.uptime_s/60).toFixed(1)+" min","uptime");
  h+="</div><h2>resources</h2><table><tr><th>resource</th><th>used</th>"+
     "<th>total</th><th></th></tr>";
  for(const k of Object.keys(c.total)){
    const t=c.total[k],a=c.available[k]??0,u=t-a;
    h+=`<tr><td>${esc(k)}</td><td>${u.toFixed(1)}</td>`+
       `<td>${t.toFixed(1)}</td><td>${bar(t?u/t:0)}</td></tr>`;
  }
  return h+"</table>";
}
async function nodes(){
  const ns=await J("nodes");
  let h="<h2>nodes</h2><table><tr><th>node</th><th>state</th>"+
   "<th>head</th><th>resources</th><th>labels</th><th>load</th>"+
   "<th>memory</th><th>workers rss</th></tr>";
  for(const n of ns){
    const s=n.host_stats||{};
    h+=`<tr><td>${esc(n.node_id)}</td>`+
     `<td>${n.alive?"ALIVE":"DEAD "+esc(n.death_cause||"")}</td>`+
     `<td>${n.is_head?"*":""}</td><td>${cell(n.resources)}</td>`+
     `<td>${cell(n.labels)}</td>`+
     `<td>${s.load_1m!=null?bar((s.load_1m||0)/(s.num_cpus||1),
           s.load_1m+" / "+s.num_cpus+" cpus"):""}</td>`+
     `<td>${s.mem_used_pct!=null?bar(s.mem_used_pct/100):""}</td>`+
     `<td>${s.workers_rss_mb!=null?
           s.workers_rss_mb+" MB ("+(s.num_workers||0)+"w)":""}</td></tr>`;
  }
  return h+"</table>";
}
async function workers(){
  return "<h2>workers</h2>"+table(await J("workers"),
   ["node_id","worker_id","pid","state","actor_id","inflight_tasks",
    "blocked_depth","env_hash","age_s"]);
}
async function actors(){return "<h2>actors</h2>"+table(await J("actors"))}
async function tasks(){
  const sum=await J("task_summary"),evs=await J("tasks");
  return "<h2>summary</h2>"+table([sum])+
    "<h2>recent events</h2>"+table(evs.slice(-60).reverse());
}
async function pgs(){
  return "<h2>placement groups</h2>"+table(await J("placement_groups"))}
function fmtMs(s){return s==null?"—":(s*1000).toFixed(s<0.01?2:0)+" ms"}
function spark(label,vals){
  const nums=vals.map(v=>v==null?0:v), w=240, hh=36;
  const max=Math.max(...nums,1e-9);
  const pts=nums.map((v,i)=>
    `${(i/Math.max(nums.length-1,1))*w},${hh-1-(v/max)*(hh-3)}`
  ).join(" ");
  return `<div class=kpi><svg width="${w}" height="${hh}">`+
    `<polyline fill="none" stroke="#4f8ef7" stroke-width="1.5" `+
    `points="${pts}"/></svg><span>${esc(label)} · max `+
    `${Math.round(max*100)/100}</span></div>`;
}
async function metricsTab(){
  const m=await J("metrics_summary");
  if(m.error) return "<i class=none>"+esc(m.error)+"</i>";
  if(!m.enabled)
    return "<i class=none>metrics disabled (RAY_TPU_METRICS=0)</i>";
  let h="<div class=kpis>";
  h+=kpi(m.sources,"processes scraped");
  h+=kpi(m.tasks_done_total,"tasks done");
  h+=kpi(fmtMs(m.queue_wait.p95),"queue wait p95 ≤");
  h+=kpi(fmtMs(m.e2e.p95),"e2e p95 ≤");
  h+=kpi(m.shm_pool_hit_rate==null?"—":
         Math.round(m.shm_pool_hit_rate*100)+"%","shm pool hit rate");
  h+=kpi(m.lease_outstanding,"leased outstanding");
  h+="</div><h2>phase latency (last "+m.window_s+"s, bucket upper "+
     "bounds)</h2>";
  h+=table(["queue_wait","exec","e2e"].map(p=>
    Object.assign({phase:p},m[p])),["phase","count","p50","p95","p99"]);
  h+="<h2>trends (per scrape)</h2><div class=kpis>";
  h+=spark("tasks/s",m.ring.map(r=>r.tasks_per_s));
  h+=spark("queue p95 ms",m.ring.map(r=>r.queue_p95_ms));
  h+=spark("wire frames/s",m.ring.map(r=>r.wire_frames_per_s));
  h+=spark("pull in-flight MB",m.ring.map(r=>r.pull_inflight_mb));
  return h+"</div>";
}
async function serveApps(){
  const apps=await J("serve_applications");
  const names=Object.keys(apps);
  if(!names.length) return "<i class=none>no applications</i>";
  let h="";
  for(const a of names){
    const rec=apps[a];
    h+=`<h2>${esc(a)} <small>(${esc(rec.route_prefix)} → `+
       `${esc(rec.ingress)})</small></h2>`;
    h+=table(Object.entries(rec.deployments).map(([d,v])=>
       Object.assign({deployment:d},v)),
       ["deployment","live_replicas","target_replicas",
        "ongoing_requests","autoscaling"]);
  }
  return h;
}
async function jobs(){
  const js=await J("jobs"),logs=await J("logs");
  return "<h2>jobs</h2>"+table(js)+"<h2>logs</h2>"+
    (Array.isArray(logs)&&logs.length?logs.map(f=>
     `<a href="/api/logs/${esc(f)}">${esc(f)}</a>`).join("<br>")
     :"<i class=none>none</i>");
}
function nav(){
  document.getElementById("nav").innerHTML=Object.keys(TABS).map(t=>
   `<button class="${t===cur?"on":""}" onclick="go('${t}')">${t}</button>`
  ).join("");
}
async function go(t){cur=t;nav();await refresh()}
async function refresh(){
  try{
    document.getElementById("out").innerHTML=await TABS[cur]();
    document.getElementById("age").textContent=
      "updated "+new Date().toLocaleTimeString();
  }catch(e){
    document.getElementById("out").innerHTML=
      "<i class=none>"+esc(e)+"</i>";
  }
}
nav();refresh();setInterval(refresh,4000);
</script></body></html>"""


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> int:
    """Serve the dashboard from the driver; returns the bound port."""
    global _SERVER
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ray_tpu.util import state as state_api
    from ray_tpu.util.metrics import DEFAULT_REGISTRY

    def api(path: str):
        from urllib.parse import parse_qs, urlsplit
        url = urlsplit(path)
        path, query = url.path, parse_qs(url.query)
        if path.startswith("logs"):
            from ray_tpu.job_submission import default_client
            client = default_client()
            parts = path.split("/", 1)
            if len(parts) == 1 or not parts[1]:
                return client.list_log_files()
            lines = int(query.get("lines", ["200"])[0])
            return {"job_id": parts[1],
                    "lines": client.tail_logs(parts[1], lines)}
        if path == "jobs":
            import dataclasses as _dc

            from ray_tpu.job_submission import default_client
            return [_dc.asdict(j) for j in
                    default_client().list_jobs()]
        if path == "actor_summary":
            return state_api.summarize_actors()
        if path == "nodes":
            return state_api.list_nodes()
        if path == "workers":
            return state_api.list_workers()
        if path == "usage":
            return state_api.usage_stats()
        if path == "actors":
            return state_api.list_actors()
        if path == "tasks":
            return state_api.list_tasks()
        if path == "task_summary":
            return state_api.summarize_tasks()
        if path == "placement_groups":
            return state_api.list_placement_groups()
        if path == "cluster":
            return {"total": state_api.cluster_resources(),
                    "available": state_api.available_resources(),
                    "object_store": state_api.object_store_stats()}
        if path == "serve_applications":
            try:
                import ray_tpu
                from ray_tpu.serve import _CONTROLLER_NAME
                controller = ray_tpu.get_actor(_CONTROLLER_NAME)
            except ValueError:
                return {}          # serve not running
            return ray_tpu.get(
                controller.list_applications.remote(), timeout=10)
        if path == "timeline":
            from ray_tpu.util.metrics import timeline
            return timeline()
        if path == "metrics_summary":
            from ray_tpu._private import context as _context
            return _context.get_ctx().state_op("metrics_summary")
        raise KeyError(path)

    last_cluster_text: list = [None]

    def metrics_text() -> str:
        """Cluster-aggregated exposition when a runtime is attached
        (r11: every process's registry merged with node/worker
        labels); the head-local registry otherwise — a dashboard
        started without init() keeps scraping something. A transient
        collect failure re-serves the LAST cluster exposition rather
        than flipping to the unlabeled head-local schema (a phantom
        label change would fork every series Prometheus-side)."""
        from ray_tpu._private import context as _context
        from ray_tpu._private import metrics_plane as _mp
        ctx = _context.maybe_ctx()
        if ctx is not None and _mp.enabled():
            try:
                merged = ctx.state_op("metrics_dump")
                if merged:
                    text = _mp.prometheus_text(merged)
                    last_cluster_text[0] = text
                    return text
            except Exception:
                pass           # head unreachable: degrade below
            if last_cluster_text[0] is not None:
                return last_cluster_text[0]   # stale beats schema flip
        return DEFAULT_REGISTRY.prometheus_text()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            try:
                if self.path == "/" or self.path == "/index.html":
                    body = _INDEX_HTML.encode()
                    ctype = "text/html"
                elif self.path == "/metrics":
                    body = metrics_text().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.startswith("/api/"):
                    body = json.dumps(api(self.path[5:]),
                                      default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
            except BaseException as e:  # noqa: BLE001
                body = json.dumps({"error": repr(e)}).encode()
                ctype = "application/json"
                self.send_response(500)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    _SERVER = ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=_SERVER.serve_forever, daemon=True).start()
    return _SERVER.server_address[1]


def stop_dashboard() -> None:
    global _SERVER
    if _SERVER is not None:
        _SERVER.shutdown()
        _SERVER = None
