"""Channel-backed compiled DAG execution (the aDAG fast path).

Parity: reference python/ray/dag/compiled_dag_node.py (CompiledDAG with
persistent per-actor exec loops :135-224, execute :2118 returning
CompiledDAGRef) over shared_memory_channel transport — re-designed for
this stack: compilation allocates one mutable channel per producer
node (single writer, one reader slot per consumer, plus the driver for
outputs), then installs a long-running exec loop on every actor via the
``__rtpu_apply__`` escape hatch. `execute()` writes the input into the
input channel and returns a CompiledDAGRef whose `get()` reads the
output channel — no task submission, object store traffic, or driver
hop between stages.

r13: channels come in two transports behind one endpoint API —
same-box mapped-shm rings (experimental/channel.py) and the cross-host
wire transport (experimental/wire_channel.py, tensors over the
Envelope `raw` zero-copy path). ``channel_transport`` selects per
compile: "shm" (default), "wire", or "auto" (wire for any edge whose
endpoints report different host IPs). Both transports are multi-slot
rings (RAY_TPU_CHANNEL_RING_DEPTH, default 2), so a producer can
publish message m and start computing m+1 while consumers drain m —
the transfer/compute overlap the MPMD pipeline (train/pipeline.py)
schedules against. Exec loops run under a per-stage trace context:
channel wait/write/read spans and per-execute compute spans land in
the r9 flight recorders, so `util.tracing.task_timeline()` shows stage
occupancy and bubbles.
"""
from __future__ import annotations

import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

import ray_tpu
from ray_tpu._private import tracing_plane as _tp
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelTimeout, _ring_depth)
from ray_tpu.experimental.wire_channel import (WireChannel, _apply_serve,
                                               _my_ip, serve_channel)


class AbortFlag:
    """One shared u64 in shm that exec loops poll between bounded channel
    reads, so a dead upstream actor can never wedge a loop forever: the
    driver raises the flag at teardown and every surviving loop exits at
    its next poll (reference CompiledDAG cancels exec loops instead).

    The segment is HOST-LOCAL to its creator (`host` rides the pickle):
    on any other host is_set() reports False — a cross-host wire-
    transport stage cannot see the driver's /dev/shm, and treating the
    unmappable segment as "aborted" would kill every remote loop at its
    first idle poll. Remote stages learn of teardown through their
    channels instead (close frames / dropped connections)."""

    def __init__(self, name: str, host: str = ""):
        self.name = name
        self.host = host
        self._mv = None
        self._reachable: Optional[bool] = None

    @classmethod
    def create(cls) -> "AbortFlag":
        from ray_tpu._private.object_store import _create_segment
        from ray_tpu._private.specs import SESSION_TAG
        name = f"rtpu_{SESSION_TAG}_abort_{uuid.uuid4().hex[:12]}"
        _create_segment(name, memoryview(bytes(8)))
        return cls(name, _my_ip())

    def _map(self):
        if self._mv is None:
            from ray_tpu._private.object_store import _map_segment
            self._mv = _map_segment(self.name, 8)
        return self._mv

    def set(self) -> None:
        struct.pack_into("<Q", self._map(), 0, 1)

    def is_set(self) -> bool:
        if self._reachable is None:
            self._reachable = (not self.host) or self.host == _my_ip()
        if not self._reachable:
            return False               # other host: channels signal
        try:
            return struct.unpack_from("<Q", self._map(), 0)[0] != 0
        except BaseException:
            # same host but the segment is gone: the driver destroyed
            # the DAG before this loop's first poll mapped it == abort
            return True

    def destroy(self) -> None:
        from ray_tpu._private.object_store import unlink_segment
        self._mv = None
        unlink_segment(self.name)

    def __reduce__(self):
        return (AbortFlag, (self.name, self.host))


class _Err:
    """Error envelope forwarded through downstream channels so one
    failing node poisons the execution, not the pipeline."""

    def __init__(self, repr_: str):
        self.repr = repr_


class LoopWatchdog:
    """Dead-stage watchdog shared by ChannelCompiledDAG and the MPMD
    pipeline (train/pipeline.py): runs the driver's blocking channel
    reads/writes in bounded slices and, between slices, checks whether
    any long-lived exec-loop task ref resolved with an error (a loop
    only ERRORS when its actor died — normal exits return a value).
    A dead stage then surfaces as a RuntimeError at the channel op —
    execute()/run_step() — instead of hanging until the caller's
    timeout, and the abort flag is raised so every surviving loop
    unwedges at its next poll. The first error is memoized: a dead
    stage stays dead."""

    def __init__(self, loop_refs: List[Any], abort: AbortFlag,
                 what: str):
        self._refs = loop_refs          # by reference: callers append
        self._abort = abort
        self._what = what               # e.g. "compiled DAG stage"
        self._err: Optional[BaseException] = None

    def failed(self) -> Optional[BaseException]:
        if self._err is not None or not self._refs:
            return self._err
        try:
            done, _ = ray_tpu.wait(self._refs,
                                   num_returns=len(self._refs),
                                   timeout=0)
        except Exception:
            return None
        for ref in done:
            try:
                ray_tpu.get(ref, timeout=5.0)
            except BaseException as e:  # noqa: BLE001
                self._err = e
                return e
        return None

    def op(self, op, timeout: Optional[float], what: str):
        """Run `op(slice_timeout)` until it returns, the deadline
        expires, or a stage death converts into a RuntimeError."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            chunk = (1.0 if remaining is None
                     else min(1.0, max(0.05, remaining)))
            try:
                return op(chunk)
            except (ChannelTimeout, ChannelClosed) as e:
                # a wire reader sees the closed conn BEFORE the dead
                # actor's exec-loop ref resolves — give the failure a
                # moment to land so the caller gets the real cause
                polls = 20 if isinstance(e, ChannelClosed) else 1
                err = None
                for i in range(polls):
                    err = self.failed()
                    if err is not None:
                        break
                    if i + 1 < polls:
                        time.sleep(0.1)
                if err is not None:
                    try:
                        self._abort.set()
                    except BaseException:
                        pass
                    raise RuntimeError(
                        f"{self._what} died mid-pipeline ({what}): "
                        f"{err}") from err
                if isinstance(e, ChannelClosed):
                    raise
                if remaining is not None and remaining <= chunk:
                    raise


def _exec_loop(instance, method_name: str, in_channels: List[Any],
               in_reader_idx: List[int], arg_spec: List[Tuple],
               kw_spec: Dict[str, Tuple], out_channel: Any,
               abort: AbortFlag) -> int:
    """Runs INSIDE the actor (one long-lived call): read inputs, run the
    method, write the result; repeats until the upstream closes or the
    driver raises the abort flag (bounded reads — a dead peer can't
    wedge this loop forever)."""
    readers: List[Any] = []
    writer = None

    def bounded(fn, *a, **kw):
        while True:
            try:
                return fn(*a, timeout=1.0, **kw)
            except ChannelTimeout:
                if abort.is_set():
                    raise ChannelClosed("aborted") from None

    executed = 0
    try:
        for ch, i in zip(in_channels, in_reader_idx):
            readers.append(ch.reader(i))
        writer = out_channel.writer()
        # Per-stage trace lane: channel spans + compute spans inside
        # this loop share one trace id, so task_timeline() renders this
        # stage's occupancy as one coherent lane. Zero cost when
        # RAY_TPU_TRACE=0.
        if _tp.enabled():
            _tp.set_current(_tp.new_id(), 0)
        while True:
            vals: List[Any] = [None] * len(readers)
            err: Any = None
            try:
                if len(readers) == 1:
                    vals[0] = bounded(readers[0].read)
                else:
                    # overlap schedule (reference dag_node_operation.py
                    # intent): consume multi-node inputs in ARRIVAL
                    # order — a slow upstream never head-of-line-blocks
                    # the inputs that are already published
                    pending = set(range(len(readers)))
                    poll = 0.005
                    while pending:
                        progressed = False
                        for i in list(pending):
                            try:
                                vals[i] = readers[i].read(timeout=poll)
                                pending.discard(i)
                                progressed = True
                            except ChannelTimeout:
                                pass
                        if progressed:
                            poll = 0.005
                        else:
                            # idle between executes: back the poll off
                            # so a parked DAG doesn't burn a core
                            poll = min(poll * 2, 0.25)
                            if abort.is_set():
                                raise ChannelClosed("aborted")
            except ChannelClosed:
                # short ack wait: at teardown the driver may never ack
                # the final output, and a 5s stall here would outlive
                # the driver's loop-exit budget and get this actor
                # killed
                writer.close(timeout=0.5)
                return executed
            for v in vals:
                if isinstance(v, _Err):
                    err = v
                    break
            if err is None:
                def resolve(spec):
                    kind, payload = spec
                    return vals[payload] if kind == "n" else payload
                try:
                    args = [resolve(s) for s in arg_spec]
                    kwargs = {k: resolve(s) for k, s in kw_spec.items()}
                    with _tp.span("dag", f"exec:{method_name}"):
                        result = getattr(instance, method_name)(
                            *args, **kwargs)
                except BaseException as e:  # noqa: BLE001
                    import traceback
                    result = _Err("".join(traceback.format_exception(e)))
            else:
                result = err
            try:
                bounded(writer.write, result)
            except ChannelClosed:
                return executed
            executed += 1
    finally:
        # the loop's trace context must not outlive it — later tasks on
        # this actor would stamp their spans into the dead DAG's trace
        _tp.clear_current()
        # transport resources (wire: reader conns + writer-side server)
        # release with the loop, so surviving actors don't leak sockets
        for r in readers:
            try:
                r.release()
            except BaseException:
                pass
        if writer is not None:
            try:
                writer.release()
            except BaseException:
                pass


class CompiledDAGRef:
    """Result handle for one execute() (reference CompiledDAGRef):
    `get()` reads the output channel(s) in order. ray_tpu.get() accepts
    it directly."""

    def __init__(self, dag: "ChannelCompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: Optional[float] = 30.0):
        if self._consumed:
            raise ValueError("CompiledDAGRef can only be read once")
        value = self._dag._fetch(self._seq, timeout)
        self._consumed = True          # only after a successful fetch
        if isinstance(value, _Err):
            raise RuntimeError(f"compiled DAG node failed:\n{value.repr}")
        if isinstance(value, list):
            for v in value:
                if isinstance(v, _Err):
                    raise RuntimeError(
                        f"compiled DAG node failed:\n{v.repr}")
        return value


class ChannelCompiledDAG:
    """Channel-transport compiled DAG (single InputNode, every actor
    hosts at most one node)."""

    def __init__(self, output, buffer_size_bytes: int = 1 << 20,
                 transport: str = "shm",
                 ring_depth: Optional[int] = None):
        from ray_tpu.dag import (ClassMethodNode, CompiledDAG, InputNode,
                                 MultiOutputNode)
        if transport not in ("shm", "wire", "auto"):
            raise ValueError(
                f"channel_transport must be shm|wire|auto, "
                f"got {transport!r}")
        self._buffer = buffer_size_bytes
        self._depth = _ring_depth(ring_depth)
        # ring depth bounds unread in-flight executes per channel;
        # one extra execute may be mid-write
        self._max_in_flight = self._depth + 1
        base = CompiledDAG(output)          # reuse toposort + validation
        self._order = base._order
        self._input = base._input
        if self._input is None:
            raise ValueError("channel-mode DAG needs an InputNode")
        self._output = output
        nodes = [n for n in self._order
                 if isinstance(n, ClassMethodNode)]
        if not nodes:
            raise ValueError("channel-mode DAG needs actor nodes")
        actors = [n.actor for n in nodes]
        if len({a._actor_id for a in actors}) != len(actors):
            raise ValueError(
                "channel mode requires each actor to host exactly one "
                "DAG node (an actor's exec loop owns it exclusively)")
        out_nodes = (list(output.outputs)
                     if isinstance(output, MultiOutputNode) else [output])
        for o in out_nodes:
            if not isinstance(o, ClassMethodNode):
                raise ValueError("DAG outputs must be actor nodes")
        self._out_nodes = out_nodes

        # --- consumers per producer (input node included)
        consumers: Dict[int, List] = {id(self._input): []}
        for n in nodes:
            consumers[id(n)] = []
        for n in nodes:
            seen_up = set()
            for up in n.upstream:
                # dedup: a node passing the same upstream twice still
                # reads it through ONE reader slot
                if id(up) in seen_up:
                    continue
                seen_up.add(id(up))
                if isinstance(up, (ClassMethodNode, InputNode)):
                    consumers[id(up)].append(n)
        # the driver reads every output node's channel
        n_extra = {id(n): 0 for n in nodes}
        for o in out_nodes:
            n_extra[id(o)] += 1

        # --- transport per producer edge
        from ray_tpu.actor import ActorMethod

        def _apply(actor, fn, *args):
            return ActorMethod(actor, "__rtpu_apply__", {}).remote(
                cloudpickle.dumps(fn), *args)

        host_of: Dict[int, str] = {}
        if transport == "auto":
            # one round trip per actor, compile-time only: every
            # endpoint reports its host IP; edges whose endpoints
            # disagree go wire, same-host edges stay shm
            refs = [_apply(n.actor, lambda inst: _my_ip())
                    for n in nodes]
            for n, ip in zip(nodes, ray_tpu.get(refs, timeout=60)):
                host_of[id(n)] = ip
            driver_ip = _my_ip()

        def _edge_transport(key, cons) -> str:
            if transport != "auto":
                return transport
            ips = {host_of.get(id(c), driver_ip) for c in cons}
            ips.add(host_of.get(key, driver_ip))   # writer's host
            if n_extra.get(key, 0) or key == id(self._input):
                ips.add(driver_ip)                 # driver endpoint
            return "shm" if len(ips) <= 1 else "wire"

        # --- allocate channels (wire producers bind their server in
        # the writer's process before any loop starts)
        from ray_tpu._private.specs import SESSION_TAG
        self._channels: Dict[int, Any] = {}
        node_label = {id(self._input): "in"}
        for n in nodes:
            node_label[id(n)] = n.method_name
        pending_serve: List[Tuple[int, Any, str, int]] = []
        for key, cons in consumers.items():
            extra = n_extra.get(key, 0)
            n_readers = len(cons) + extra
            if n_readers == 0:
                continue
            label = node_label.get(key, "")
            if _edge_transport(key, cons) == "shm":
                self._channels[key] = Channel.create(
                    capacity=buffer_size_bytes, n_readers=n_readers,
                    depth=self._depth, label=label)
            elif key == id(self._input):
                # driver is the writer: serve locally
                self._channels[key] = serve_channel(
                    capacity=buffer_size_bytes, n_readers=n_readers,
                    depth=self._depth, label=label)
            else:
                name = (f"rtpu_{SESSION_TAG}_wch_"
                        f"{uuid.uuid4().hex[:12]}")
                producer = next(n for n in nodes if id(n) == key)
                ref = _apply(producer.actor, _apply_serve, name,
                             buffer_size_bytes, n_readers, self._depth,
                             label)
                pending_serve.append((key, ref, name, n_readers))
        for key, ref, name, n_readers in pending_serve:
            addr = ray_tpu.get(ref, timeout=60)
            self._channels[key] = WireChannel(
                name, buffer_size_bytes, n_readers, self._depth,
                addr, node_label.get(key, ""))
        # reader slot assignment: consumers take slots in order; the
        # driver takes the last slot(s)
        slot: Dict[Tuple[int, int], int] = {}
        for key, cons in consumers.items():
            for i, c in enumerate(cons):
                slot[(key, id(c))] = i

        # --- install exec loops
        self._abort = AbortFlag.create()
        self._loop_refs = []
        self._loop_actors = []
        for n in nodes:
            in_chs, in_idx, arg_spec, kw_spec = [], [], [], {}
            seen_inputs: Dict[int, int] = {}

            def input_index(up) -> int:
                if id(up) not in seen_inputs:
                    seen_inputs[id(up)] = len(in_chs)
                    in_chs.append(self._channels[id(up)])
                    in_idx.append(slot[(id(up), id(n))])
                return seen_inputs[id(up)]

            for a in n.args:
                if isinstance(a, (ClassMethodNode, InputNode)):
                    arg_spec.append(("n", input_index(a)))
                else:
                    arg_spec.append(("c", a))
            for k, v in n.kwargs.items():
                if isinstance(v, (ClassMethodNode, InputNode)):
                    kw_spec[k] = ("n", input_index(v))
                else:
                    kw_spec[k] = ("c", v)
            method = ActorMethod(n.actor, "__rtpu_apply__", {})
            self._loop_refs.append(method.remote(
                cloudpickle.dumps(_exec_loop), n.method_name, in_chs,
                in_idx, arg_spec, kw_spec, self._channels[id(n)],
                self._abort))
            self._loop_actors.append(n.actor)

        # --- driver endpoints
        self._in_writer = self._channels[id(self._input)].writer()
        self._out_readers = []
        taken: Dict[int, int] = {}
        for o in out_nodes:
            ch = self._channels[id(o)]
            base_slot = len(consumers[id(o)]) + taken.get(id(o), 0)
            taken[id(o)] = taken.get(id(o), 0) + 1
            self._out_readers.append(ch.reader(base_slot))
        self._multi = isinstance(output, MultiOutputNode)
        self._lock = threading.Lock()
        self._next_seq = 0
        self._fetched: Dict[int, Any] = {}
        self._partial_row: List[Any] = []
        self._read_seq = 0
        self.num_executions = 0
        self._torn_down = False
        self._watch = LoopWatchdog(self._loop_refs, self._abort,
                                   "compiled DAG stage")

    # ------------------------------------------------------------- api
    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        if len(args) != 1:
            raise TypeError(f"DAG takes exactly 1 input, got {len(args)}")
        with self._lock:
            # self-drain: pull finished results into _fetched so the
            # pipeline's bounded ring channels never back up into an
            # unbounded blocking input write
            while self._next_seq - self._read_seq >= self._max_in_flight:
                self._read_row(60.0)
            with _tp.span("dag", "execute", root=True):
                self._watch.op(
                    lambda t: self._in_writer.write(args[0], timeout=t),
                    60.0, "writing DAG input")
            seq = self._next_seq
            self._next_seq += 1
            self.num_executions += 1
        return CompiledDAGRef(self, seq)

    def _read_row(self, timeout: Optional[float]) -> None:
        """Read one full output row (resuming a partial row) into
        _fetched. Caller holds _lock."""
        while len(self._partial_row) < len(self._out_readers):
            r = self._out_readers[len(self._partial_row)]
            # _partial_row survives a timeout mid-row: each reader's
            # read consumes its ring slot, so a retry must RESUME at
            # the first unread output, never re-read consumed ones
            self._partial_row.append(self._watch.op(
                lambda t, r=r: r.read(t), timeout, "reading DAG output"))
        outs, self._partial_row = self._partial_row, []
        self._fetched[self._read_seq] = (outs if self._multi
                                         else outs[0])
        self._read_seq += 1

    def _fetch(self, seq: int, timeout: Optional[float]):
        with self._lock:
            while self._read_seq <= seq:
                self._read_row(timeout)
            return self._fetched.pop(seq)

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        try:
            self._in_writer.close()
        except BaseException:
            pass
        # abort flag unwedges loops blocked on a dead peer's channel
        try:
            self._abort.set()
        except BaseException:
            pass
        remaining = list(zip(self._loop_refs, self._loop_actors))
        try:
            ray_tpu.get(self._loop_refs, timeout=5.0)
            remaining = []
        except BaseException:
            pass
        # kill loops that still haven't exited — destroying segments
        # under a live reader would leave its thread stuck for the
        # actor's lifetime
        for ref, actor in remaining:
            try:
                done, _ = ray_tpu.wait([ref], timeout=0.1)
                if not done:
                    ray_tpu.kill(actor)
            except BaseException:
                pass
        for r in self._out_readers:
            try:
                r.release()
            except BaseException:
                pass
        try:
            self._in_writer.release()
        except BaseException:
            pass
        for ch in self._channels.values():
            try:
                ch.destroy()
            except BaseException:
                pass
        try:
            self._abort.destroy()
        except BaseException:
            pass

    def __del__(self):
        try:
            self.teardown()
        except BaseException:
            pass
