"""Mutable shared-memory channels: the compiled-DAG fast path.

Parity: reference python/ray/experimental/channel/shared_memory_channel.py
+ src/ray/core_worker/experimental_mutable_object_manager.cc — a
fixed-capacity single-writer / multi-reader shm ring that is REUSED for
every message, so a compiled DAG's hops exchange data with one memcpy
and zero store round-trips, task submissions, or driver hops.

Protocol (one segment per channel, `depth` payload slots):

    u64 magic | u64 n_readers | u64 seq | u64 depth
    u64 acks[n_readers] | u64 lens[depth]
    ... depth * capacity payload bytes ...

Messages are numbered from 1; message s lives in slot (s-1) % depth.
The writer waits until every reader's ack is >= s - depth (the slot's
previous occupant is fully consumed), copies the payload into the slot,
stores lens[slot], then publishes seq=s — a single aligned u64 store,
which is atomic on every platform XLA targets. Reader i polls seq until
it reaches its expected value, copies the payload out, then stores
ack[i]=s. Each header word has exactly one writer, so no cross-process
atomics beyond aligned stores are needed. Blocking is adaptive spin ->
sleep polling (the reference uses futex-backed semaphores; at the ~µs
scales involved polling is competitive and portable).

depth > 1 (r13, default RAY_TPU_CHANNEL_RING_DEPTH=2) is what makes
transfer/compute OVERLAP possible: with a single slot the writer blocks
until every reader consumed the previous message, serializing a
pipeline stage's send with its neighbor's compute; with two slots the
writer publishes message s and immediately starts computing s+1 while
the reader drains s (double buffering). MPMD pipeline stages depend on
this (train/pipeline.py).

Channels are HOST-LOCAL (the segment lives in this host's /dev/shm),
like the reference's shm channels; cross-host DAG edges ride the r13
wire transport instead (experimental/wire_channel.py).
"""
from __future__ import annotations

import pickle
import struct
import time
import uuid
from typing import Any, Optional

import cloudpickle

from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.object_store import (_create_segment, _map_segment,
                                           unlink_segment)

_MAGIC = 0x52545055_4348414E          # "RTPUCHAN"
_CLOSED_LEN = (1 << 63) - 1           # writer closed the channel
_ERROR_FLAG = 1 << 62                 # payload pickles an error repr
# Device-channel fast path (reference torch_tensor_nccl_channel.py
# intent, re-designed for TPU processes): the payload is a RAW
# ndarray — u32 meta_len + pickled (dtype, shape, is_device) + bytes —
# written with ONE memcpy from the producer's host buffer and consumed
# by a single jax.device_put straight from the mapped segment. No
# pickle stream, no intermediate copies on the hot edge.
_RAW_FLAG = 1 << 61
_LEN_MASK = (1 << 61) - 1


def _raw_ok(dtype) -> bool:
    # object/structured dtypes need the pickle path; the dtype OBJECT
    # (not .str, which is lossy for bfloat16 — '<V2' — and structured
    # dtypes) travels pickled in the meta
    return not (dtype.hasobject or dtype.fields)


def _array_payload(value):
    """(meta, contiguous ndarray) for raw transport, or None for the
    pickle path. jax.Arrays round-trip as jax.Arrays (device_put on the
    consumer); plain numpy stays numpy (subclasses like MaskedArray
    take the pickle path — coercion would drop their semantics)."""
    import numpy as np
    if type(value) is np.ndarray and _raw_ok(value.dtype):
        arr = np.ascontiguousarray(value)
        return pickle.dumps((arr.dtype, arr.shape, False)), arr
    try:
        import jax
    except Exception:                  # pragma: no cover - jax is baked in
        return None
    if isinstance(value, jax.Array):
        try:
            arr = np.ascontiguousarray(np.asarray(value))   # D2H copy
        except Exception:
            return None                # e.g. sharded across devices
        if not _raw_ok(arr.dtype):
            return None
        return pickle.dumps((arr.dtype, arr.shape, True)), arr
    return None


class ChannelClosed(Exception):
    pass


class ChannelTimeout(Exception):
    pass


def _ring_depth(depth: Optional[int]) -> int:
    if depth is None:
        from ray_tpu._private.config import CONFIG
        depth = int(CONFIG.channel_ring_depth)
    return max(1, int(depth))


def _wait(predicate, timeout: Optional[float], what: str):
    deadline = None if timeout is None else time.monotonic() + timeout
    spins = 0
    sleep = 20e-6
    while True:
        if predicate():
            return
        spins += 1
        if spins < 200:
            continue                   # hot spin for µs-scale waits
        if deadline is not None and time.monotonic() > deadline:
            raise ChannelTimeout(f"timed out waiting for {what}")
        # progressive backoff: an idle exec loop parked between
        # executes settles at ~1ms polls instead of burning a core
        time.sleep(sleep)
        sleep = min(sleep * 1.5, 1e-3)


def _wait_words(ch: "Channel", offset: int, count: int, value: int,
                timeout: Optional[float], what: str) -> None:
    """Wait until the `count` u64 header words at `offset` are all
    >= value. Native path (ray_tpu/native/core.c) spins with the GIL
    RELEASED — the Python fallback holds the GIL between checks, which
    on few-core hosts starves the very peer being waited on."""
    from ray_tpu import native
    if native.available():
        # ≤100ms native slices: the C spin releases the GIL but also
        # blocks Python signal delivery — slicing keeps Ctrl-C (and
        # teardown exceptions) responsive even on timeout=None waits
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        mv = ch._map()
        while True:
            if deadline is None:
                chunk = 0.1
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ChannelTimeout(
                        f"timed out waiting for {what}")
                chunk = min(remaining, 0.1)
            if native.wait_u64s_ge(mv, offset, count, value, chunk):
                return
        # not reached
    _wait(lambda: all(ch._u64(offset + 8 * i) >= value
                      for i in range(count)), timeout, what)


class Channel:
    """Descriptor + mapping for one channel. Create once (driver side),
    then hand to exactly one writer and `n_readers` readers (each with a
    distinct reader_index)."""

    transport = "shm"

    def __init__(self, name: str, capacity: int, n_readers: int,
                 depth: int = 1, label: str = ""):
        self.name = name
        self.capacity = capacity
        self.n_readers = n_readers
        self.depth = max(1, int(depth))
        self.label = label or name[-6:]
        self._mv: Optional[memoryview] = None

    @classmethod
    def create(cls, capacity: int = 1 << 20, n_readers: int = 1,
               depth: Optional[int] = None, label: str = "") -> "Channel":
        from ray_tpu._private.specs import SESSION_TAG
        depth = _ring_depth(depth)
        name = f"rtpu_{SESSION_TAG}_ch_{uuid.uuid4().hex[:12]}"
        header = 32 + 8 * n_readers + 8 * depth
        buf = bytearray(header + depth * capacity)
        struct.pack_into("<QQQQ", buf, 0, _MAGIC, n_readers, 0, depth)
        ch = cls(name, capacity, n_readers, depth, label)
        _create_segment(name, memoryview(bytes(buf)))
        return ch

    # ------------------------------------------------------- low level
    def _map(self) -> memoryview:
        if self._mv is None:
            header = 32 + 8 * self.n_readers + 8 * self.depth
            self._mv = _map_segment(
                self.name, header + self.depth * self.capacity)
            magic, n, _, d = struct.unpack_from("<QQQQ", self._mv, 0)
            if magic != _MAGIC or n != self.n_readers or d != self.depth:
                raise ValueError(f"bad channel segment {self.name}")
        return self._mv

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._map(), off)[0]

    def _set_u64(self, off: int, val: int) -> None:
        struct.pack_into("<Q", self._map(), off, val)

    def _len_off(self, slot: int) -> int:
        return 32 + 8 * self.n_readers + 8 * slot

    def _slot_off(self, slot: int) -> int:
        return (32 + 8 * self.n_readers + 8 * self.depth
                + slot * self.capacity)

    # ------------------------------------------------------- endpoints
    def writer(self) -> "ChannelWriter":
        return ChannelWriter(self)

    def reader(self, reader_index: int) -> "ChannelReader":
        return ChannelReader(self, reader_index)

    def destroy(self) -> None:
        self._mv = None
        unlink_segment(self.name)

    def __reduce__(self):
        return (Channel, (self.name, self.capacity, self.n_readers,
                          self.depth, self.label))


class ChannelWriter:
    def __init__(self, channel: Channel):
        self.ch = channel
        self._seq = channel._u64(16)

    def _acquire_slot(self, timeout: Optional[float]) -> int:
        """Wait until message self._seq+1's ring slot is consumable and
        return its index: every reader must have acked the message that
        last occupied it (s - depth). With depth > 1 the writer runs
        ahead of its readers — double buffering, the transfer/compute
        overlap the MPMD pipeline schedules depend on."""
        ch = self.ch
        s = self._seq + 1
        if s > ch.depth:
            with _tp.span("channel", f"ch.wait:{ch.label}",
                          extra={"seq": s}):
                _wait_words(ch, 32, ch.n_readers, s - ch.depth, timeout,
                            "readers to free a ring slot")
        return (s - 1) % ch.depth

    def _publish(self, slot: int, len_word: int) -> None:
        ch = self.ch
        ch._set_u64(ch._len_off(slot), len_word)
        self._seq += 1
        ch._set_u64(16, self._seq)     # publish

    def write_bytes(self, data: bytes, *, error: bool = False,
                    timeout: Optional[float] = None) -> None:
        ch = self.ch
        if len(data) > ch.capacity:
            raise ValueError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{ch.capacity}; recompile with a larger "
                f"buffer_size_bytes")
        slot = self._acquire_slot(timeout)
        with _tp.span("channel", f"ch.write:{ch.label}",
                      extra={"bytes": len(data)}):
            mv = ch._map()
            off = ch._slot_off(slot)
            mv[off:off + len(data)] = data
            self._publish(slot,
                          len(data) | (_ERROR_FLAG if error else 0))

    def write(self, value: Any, **kw) -> None:
        payload = _array_payload(value)
        if payload is not None:
            self._write_array(payload[0], payload[1], **kw)
        else:
            self.write_bytes(
                cloudpickle.dumps(value,
                                  protocol=pickle.HIGHEST_PROTOCOL),
                **kw)

    def _write_array(self, meta: bytes, arr,
                     timeout: Optional[float] = None) -> None:
        """Raw-array frame: one memcpy into the mapped slot."""
        import numpy as np
        ch = self.ch
        total = 4 + len(meta) + arr.nbytes
        if total > ch.capacity:
            raise ValueError(
                f"array of {arr.nbytes} bytes exceeds channel capacity "
                f"{ch.capacity}; recompile with a larger "
                f"buffer_size_bytes")
        slot = self._acquire_slot(timeout)
        with _tp.span("channel", f"ch.write:{ch.label}",
                      extra={"bytes": arr.nbytes}):
            mv = ch._map()
            off = ch._slot_off(slot)
            struct.pack_into("<I", mv, off, len(meta))
            mv[off + 4:off + 4 + len(meta)] = meta
            body = mv[off + 4 + len(meta):off + total]
            np.frombuffer(body, dtype=arr.dtype).reshape(
                arr.shape)[...] = arr
            self._publish(slot, total | _RAW_FLAG)

    def close(self, timeout: float = 5.0) -> None:
        """Publish the closed marker (readers raise ChannelClosed once
        they reach it — messages already in the ring drain first)."""
        ch = self.ch
        try:
            slot = self._acquire_slot(timeout)
        except ChannelTimeout:
            # A ring slot hasn't freed up: a reader is wedged or gone.
            # Stomping an unconsumed slot would silently drop data;
            # leave the ring intact — stuck readers are handled by
            # teardown.
            return
        self._publish(slot, _CLOSED_LEN)

    def release(self) -> None:
        """Transport-symmetric resource hook (wire channels shut their
        server down here); shm writers hold nothing beyond the mapping."""


class ChannelReader:
    def __init__(self, channel: Channel, reader_index: int):
        if not 0 <= reader_index < channel.n_readers:
            raise ValueError("reader_index out of range")
        self.ch = channel
        self.idx = reader_index
        # messages are numbered from seq 1; a reader may attach after
        # the writer published up to `depth` messages (exec loops start
        # async), and the writer's ack gate guarantees no slot can be
        # overwritten before every reader consumed it — so always start
        # at 1
        self._expect = 1

    def read_bytes(self, timeout: Optional[float] = None) -> bytes:
        ch = self.ch
        _wait_words(ch, 16, 1, self._expect, timeout, "message")
        length = ch._u64(ch._len_off((self._expect - 1) % ch.depth))
        if length != _CLOSED_LEN and (length & _RAW_FLAG):
            # refuse BEFORE consuming: the frame stays readable via
            # read() (decoding here would ack + advance destructively)
            raise RuntimeError(
                "read_bytes on a raw-array frame; use read()")
        data, _ = self._read_frame(timeout)
        return data

    def _read_frame(self, timeout: Optional[float]):
        ch = self.ch
        with _tp.span("channel", f"ch.read:{ch.label}",
                      extra={"seq": self._expect}):
            _wait_words(ch, 16, 1, self._expect, timeout, "message")
            slot = (self._expect - 1) % ch.depth
            length = ch._u64(ch._len_off(slot))
            if length == _CLOSED_LEN:
                raise ChannelClosed(ch.name)
            error = bool(length & _ERROR_FLAG)
            raw = bool(length & _RAW_FLAG)
            length &= _LEN_MASK
            off = ch._slot_off(slot)
            if raw:
                value = self._decode_array(length, off)
                ch._set_u64(32 + 8 * self.idx, self._expect)   # ack
                self._expect += 1
                return value, True
            data = bytes(ch._map()[off:off + length])
            ch._set_u64(32 + 8 * self.idx, self._expect)   # ack
            self._expect += 1
        if error:
            raise RuntimeError(
                f"upstream DAG node failed: {pickle.loads(data)}")
        return data, False

    def _decode_array(self, length: int, off: int):
        """Consume a raw-array frame. The device copy (jax.device_put)
        reads STRAIGHT from the mapped slot; the slot is only acked —
        and thus reusable by the writer — after the copy completes."""
        import numpy as np
        mv = self.ch._map()
        (meta_len,) = struct.unpack_from("<I", mv, off)
        dtype, shape, is_device = pickle.loads(
            bytes(mv[off + 4:off + 4 + meta_len]))
        body = mv[off + 4 + meta_len:off + length]
        view = np.frombuffer(body, dtype=dtype).reshape(shape)
        if is_device:
            import jax
            if jax.default_backend() == "cpu":
                # CPU PJRT may zero-copy-alias an aligned host buffer:
                # the returned array would mutate when the writer
                # reuses the slot after our ack. Own the bytes first.
                view = np.array(view)
            out = jax.device_put(view)
            out.block_until_ready()    # copy done before we ack
            return out
        return np.array(view)          # own the bytes before ack

    def read(self, timeout: Optional[float] = None) -> Any:
        data, raw = self._read_frame(timeout)
        if raw:
            return data
        return pickle.loads(data)

    def release(self) -> None:
        """Transport-symmetric resource hook (wire readers close their
        connection here); shm readers hold nothing beyond the mapping."""
