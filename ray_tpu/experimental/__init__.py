"""ray_tpu.experimental: channels and other pre-stable APIs (reference
python/ray/experimental/)."""
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelReader, ChannelTimeout,
                                          ChannelWriter)
from ray_tpu.experimental.wire_channel import (WireChannel,
                                               WireChannelReader,
                                               WireChannelWriter,
                                               serve_channel)

__all__ = ["Channel", "ChannelReader", "ChannelWriter", "ChannelClosed",
           "ChannelTimeout", "WireChannel", "WireChannelReader",
           "WireChannelWriter", "serve_channel"]
