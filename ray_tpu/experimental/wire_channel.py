"""Cross-host wire transport for compiled-DAG channels (r13).

Shm channels (experimental/channel.py) are same-box by construction —
the ring lives in /dev/shm. MPMD pipeline stages, however, own their
own hosts at pod scale, and their activation/grad edges must flow
process-to-process over DATA connections, never through the head
("Exploring the limits of Concurrency in ML Training on Google TPUs":
the control plane stays off the hot path). This module gives channels
that transport: the writer process hosts one listener per channel,
each reader dials it directly, and published messages are PUSHED as
Envelope frames whose tensor payload rides the r12 `raw` bulk field —
mapped straight out of the producer's contiguous buffer by the
scatter-gather emit and landed on the consumer with ONE GIL-released
memcpy (native.buf_copy) into a freshly allocated ndarray. No pickled
blobs through the object store, no store round-trips, no driver hops.

Ring semantics match the shm transport exactly: the writer keeps at
most `depth` unacked messages in flight per reader (CH_ACK frames flow
back as readers consume), so depth >= 2 double-buffers the edge — the
writer computes microbatch m+1 while m is still in flight.

Framing negotiates by observed wire MINOR (the BatchFrame discipline):
raw-payload CH_DATA frames are emitted only toward a peer that
demonstrated MINOR >= wire.CHANNEL_MIN_MINOR on its attach frame;
toward an older peer the payload falls back to the pickled body, so
old peers are unaffected — they just pay the copies this transport
exists to remove.

Endpoint API mirrors the shm classes (writer()/reader(idx), read/
write/close/release, ChannelClosed/ChannelTimeout), so the compiled-
DAG exec loops and the MPMD stage loops are transport-blind.
"""
from __future__ import annotations

import pickle
import socket
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu._private import protocol, tracing_plane as _tp
from ray_tpu._private.wire import RAW_KEY
from ray_tpu.experimental.channel import (ChannelClosed, ChannelTimeout,
                                          _array_payload, _ring_depth)

CH_ATTACH = "ch_attach"
CH_DATA = "ch_data"
CH_ACK = "ch_ack"
CH_CLOSE = "ch_close"

# Plain counters in the WIRE_STATS/OBJECT_PLANE_STATS idiom: the code
# counts its own fast-path hits so tests (and the r11 metrics plane's
# scrape-time gauges) can assert the zero-copy path actually ran.
CH_STATS = {
    "tx_raw": 0,          # raw-field frames emitted (MINOR-negotiated)
    "tx_blob": 0,         # pickled-body frames emitted (old peer / non-array)
    "rx_raw": 0,
    "rx_blob": 0,
    "landed_bytes": 0,    # raw bytes landed via the one-memcpy path
    "writes": 0,          # logical messages published (all readers)
    "reads": 0,           # logical messages consumed + acked
    "writer_block_ns": 0,  # time writers spent inside wait_writable
    "reader_wait_ns": 0,   # time readers spent parked for a message
}

# name -> _WireChannelServer living in THIS process (the writer side).
_SERVERS: Dict[str, "_WireChannelServer"] = {}
_SERVERS_LOCK = threading.Lock()


def ring_stats() -> Dict[str, int]:
    """Occupancy across every channel server in THIS process — the
    scrape-time companion to CH_STATS (the metrics plane mirrors both
    as ray_tpu_channel gauges)."""
    with _SERVERS_LOCK:
        servers = list(_SERVERS.values())
    occ = mx = 0
    for srv in servers:
        o = srv.occupancy()
        occ += o
        mx = max(mx, o)
    return {"rings": len(servers), "occupancy": occ,
            "occupancy_max": mx}


def _my_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


class _WireChannelServer:
    """Writer-side endpoint state: the per-channel listener, attached
    reader connections, and the per-reader ack clock the ring's flow
    control runs on."""

    def __init__(self, name: str, capacity: int, n_readers: int,
                 depth: int, label: str):
        self.name = name
        self.capacity = capacity
        self.n_readers = n_readers
        self.depth = depth
        self.label = label
        self._cv = threading.Condition()
        self._conns: Dict[int, protocol.Connection] = {}
        self._acked = [0] * n_readers
        self._published = 0            # highest seq fully sent
        self._dead: set = set()        # reader indices whose conn died
        self._closing = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(max(4, n_readers))
        self.port = self._listener.getsockname()[1]
        self.host = _my_ip()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"rtpu-chan-{label}",
            daemon=True)
        self._accept_thread.start()

    # -------------------------------------------------------- accepting
    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return                 # listener closed: shutdown
            conn = protocol.Connection(
                sock, self._handle, self._on_conn_closed,
                name=f"chan-{self.label}", server=True)
            conn.start()

    def _handle(self, conn: protocol.Connection, msg: dict) -> None:
        mtype = msg.get("type")
        if mtype == CH_ATTACH:
            idx = int(msg["reader"])
            with self._cv:
                if not 0 <= idx < self.n_readers:
                    conn.reply(msg, ok=False,
                               error=f"reader index {idx} out of range")
                    return
                self._conns[idx] = conn
                self._dead.discard(idx)
                conn.meta["ch_reader"] = idx
                self._cv.notify_all()
            conn.reply(msg, ok=True, depth=self.depth,
                       capacity=self.capacity)
        elif mtype == CH_ACK:
            idx = int(msg["reader"])
            with self._cv:
                if 0 <= idx < self.n_readers:
                    self._acked[idx] = max(self._acked[idx],
                                           int(msg["seq"]))
                    self._cv.notify_all()

    def _on_conn_closed(self, conn: protocol.Connection) -> None:
        idx = conn.meta.get("ch_reader")
        with self._cv:
            if self._closing or idx is None:
                return
            if self._conns.get(idx) is conn:
                self._dead.add(idx)
                self._cv.notify_all()

    # ------------------------------------------------------ writer side
    def wait_writable(self, seq: int, timeout: Optional[float]) -> list:
        """Block until every reader is attached and has acked message
        seq - depth (ring flow control), then return the live reader
        connections in index order. Raises ChannelClosed when a reader
        connection died — the pipeline cannot proceed without it."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        t0 = time.perf_counter_ns()
        try:
            with self._cv:
                while True:
                    if self._closing:
                        raise ChannelClosed(
                            f"wire channel {self.name}: writer "
                            f"endpoint shut down")
                    if self._dead:
                        raise ChannelClosed(
                            f"wire channel {self.name}: reader(s) "
                            f"{sorted(self._dead)} disconnected")
                    if (len(self._conns) == self.n_readers
                            and all(a >= seq - self.depth
                                    for a in self._acked)):
                        return [self._conns[i]
                                for i in range(self.n_readers)]
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise ChannelTimeout(
                            f"timed out waiting for wire-channel readers "
                            f"({len(self._conns)}/{self.n_readers} attached, "
                            f"acks {self._acked})")
                    self._cv.wait(0.2 if remaining is None
                                  else min(remaining, 0.2))
        finally:
            # Writer-blocked-on-ack time IS the ring-pressure signal
            # (the staleness bound binding): surface it on /metrics.
            CH_STATS["writer_block_ns"] += time.perf_counter_ns() - t0

    def occupancy(self) -> int:
        """Published-but-unacked messages for the laggiest reader —
        how full the ring is (0..depth while flow control holds)."""
        with self._cv:
            floor = min(self._acked) if self._acked else 0
            return max(0, self._published - floor)

    def live_conns(self) -> list:
        with self._cv:
            return [c for c in self._conns.values() if not c.closed]

    def shutdown(self) -> None:
        with self._cv:
            self._closing = True
            conns = list(self._conns.values())
            self._cv.notify_all()
        try:
            self._listener.close()
        except OSError:
            pass
        for c in conns:
            try:
                c.close()
            except Exception:
                pass


class WireChannel:
    """Channel descriptor whose transport is a direct writer->reader
    wire connection. Pickles freely (readers dial `addr`); the writer
    endpoint only exists in the process that called serve_channel()."""

    transport = "wire"

    def __init__(self, name: str, capacity: int, n_readers: int,
                 depth: int, addr: Tuple[str, int], label: str = ""):
        self.name = name
        self.capacity = capacity
        self.n_readers = n_readers
        self.depth = max(1, int(depth))
        self.addr = tuple(addr)
        self.label = label or name[-6:]

    def writer(self) -> "WireChannelWriter":
        with _SERVERS_LOCK:
            srv = _SERVERS.get(self.name)
        if srv is None:
            raise RuntimeError(
                f"wire channel {self.name} has no server in this "
                f"process; the writer endpoint must live where "
                f"serve_channel() ran")
        return WireChannelWriter(self, srv)

    def reader(self, reader_index: int) -> "WireChannelReader":
        return WireChannelReader(self, reader_index)

    def destroy(self) -> None:
        with _SERVERS_LOCK:
            srv = _SERVERS.pop(self.name, None)
        if srv is not None:
            srv.shutdown()

    def __reduce__(self):
        return (WireChannel, (self.name, self.capacity, self.n_readers,
                              self.depth, self.addr, self.label))


def serve_channel(name: Optional[str] = None, capacity: int = 1 << 20,
                  n_readers: int = 1, depth: Optional[int] = None,
                  label: str = "") -> WireChannel:
    """Create the writer-side endpoint (listener + ring state) in THIS
    process and return the shippable descriptor readers dial."""
    from ray_tpu._private.specs import SESSION_TAG
    depth = _ring_depth(depth)
    if name is None:
        name = f"rtpu_{SESSION_TAG}_wch_{uuid.uuid4().hex[:12]}"
    srv = _WireChannelServer(name, capacity, n_readers, depth,
                             label or name[-6:])
    with _SERVERS_LOCK:
        _SERVERS[name] = srv
    return WireChannel(name, capacity, n_readers, depth,
                       (srv.host, srv.port), label)


def _apply_serve(_instance, name: str, capacity: int, n_readers: int,
                 depth: int, label: str) -> Tuple[str, int]:
    """__rtpu_apply__ escape-hatch body: bind a channel server inside
    an actor process (the DAG compiler runs this on each wire-edge
    producer before installing exec loops) and return its address."""
    ch = serve_channel(name, capacity, n_readers, depth, label)
    return ch.addr


class WireChannelWriter:
    def __init__(self, channel: WireChannel, srv: _WireChannelServer):
        self.ch = channel
        self._srv = srv
        self._seq = 0

    def _send(self, conns: list, value: Any, error: bool,
              seq: int) -> None:
        # capacity is advisory on this transport: the reader allocates
        # exactly the payload size, and ring depth (not slot size)
        # bounds in-flight memory.
        payload = None if error else _array_payload(value)
        blob = None
        for conn in conns:
            if payload is not None and conn.peer_speaks_channel():
                meta, arr = payload
                msg = {"type": CH_DATA, "seq": seq, "meta": meta,
                       RAW_KEY: [memoryview(arr).cast("B")]}
                CH_STATS["tx_raw"] += 1
            else:
                if blob is None:
                    blob = cloudpickle.dumps(
                        value, protocol=pickle.HIGHEST_PROTOCOL)
                msg = {"type": CH_DATA, "seq": seq, "blob": blob,
                       "err": bool(error)}
                CH_STATS["tx_blob"] += 1
            try:
                conn.send(_tp.stamp(msg))
            except protocol.ConnectionClosed:
                raise ChannelClosed(
                    f"wire channel {self.ch.name}: reader "
                    f"disconnected mid-write") from None

    def write(self, value: Any, *, error: bool = False,
              timeout: Optional[float] = None) -> None:
        seq = self._seq + 1
        with _tp.span("channel", f"ch.wait:{self.ch.label}",
                      extra={"seq": seq, "transport": "wire"}):
            conns = self._srv.wait_writable(seq, timeout)
        with _tp.span("channel", f"ch.write:{self.ch.label}",
                      extra={"seq": seq, "transport": "wire"}):
            self._send(conns, value, error, seq)
        self._seq = seq
        self._srv._published = seq
        CH_STATS["writes"] += 1

    def write_bytes(self, data: bytes, *, error: bool = False,
                    timeout: Optional[float] = None) -> None:
        self.write(data, error=error, timeout=timeout)

    def close(self, timeout: float = 5.0) -> None:
        """Push the closed marker. TCP delivers in order, so readers
        drain every published message before they see it (no slot to
        stomp — strictly safer than the shm close)."""
        for conn in self._srv.live_conns():
            try:
                conn.send({"type": CH_CLOSE, "name": self.ch.name})
            except protocol.ConnectionClosed:
                pass

    def release(self) -> None:
        """Shut the writer-side server down: listener, accept thread,
        reader connections. Called when the owning exec/stage loop
        exits so surviving actors don't leak sockets."""
        with _SERVERS_LOCK:
            _SERVERS.pop(self.ch.name, None)
        self._srv.shutdown()


class WireChannelReader:
    def __init__(self, channel: WireChannel, reader_index: int,
                 attach_timeout: Optional[float] = None):
        if not 0 <= reader_index < channel.n_readers:
            raise ValueError("reader_index out of range")
        from ray_tpu._private.config import CONFIG
        self.ch = channel
        self.idx = reader_index
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._closed = False           # CH_CLOSE seen
        self._dead = False             # connection dropped
        self._conn = protocol.connect(
            channel.addr, self._handle, on_close=self._on_close,
            name=f"chan-{channel.label}-r{reader_index}")
        try:
            rep = self._conn.request(
                {"type": CH_ATTACH, "name": channel.name,
                 "reader": reader_index},
                timeout=(attach_timeout if attach_timeout is not None
                         else CONFIG.channel_wire_attach_timeout_s))
            if not rep.get("ok"):
                raise ChannelClosed(
                    f"wire channel attach refused: {rep.get('error')}")
        except BaseException:
            # a failed attach must not leak the dialed connection (and
            # its reader thread) — the caller never sees this endpoint
            self._conn.close()
            raise

    # ------------------------------------------------------- receiving
    def _handle(self, conn: protocol.Connection, msg: dict) -> None:
        mtype = msg.get("type")
        if mtype == CH_DATA:
            with self._cv:
                self._queue.append(msg)
                self._cv.notify_all()
        elif mtype == CH_CLOSE:
            with self._cv:
                self._closed = True
                self._cv.notify_all()

    def _on_close(self, conn: protocol.Connection) -> None:
        with self._cv:
            self._dead = True
            self._cv.notify_all()

    def _next(self, timeout: Optional[float]) -> dict:
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        t0 = time.perf_counter_ns()
        try:
            with self._cv:
                while True:
                    if self._queue:
                        return self._queue.popleft()
                    if self._closed or self._dead:
                        raise ChannelClosed(self.ch.name)
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    if remaining is not None and remaining <= 0:
                        raise ChannelTimeout(
                            f"timed out waiting for message on wire "
                            f"channel {self.ch.label}")
                    self._cv.wait(0.2 if remaining is None
                                  else min(remaining, 0.2))
        finally:
            CH_STATS["reader_wait_ns"] += time.perf_counter_ns() - t0

    def _land_raw(self, msg: dict):
        """One-memcpy landing: the C envelope parser handed us a
        zero-copy view of the frame's raw field; copy it GIL-released
        into a freshly allocated ndarray (the r12 land discipline) and
        device_put when the producer shipped a jax.Array."""
        import numpy as np
        dtype, shape, is_device = pickle.loads(msg["meta"])
        raw = msg[RAW_KEY]
        arr = np.empty(shape, dtype=dtype)
        from ray_tpu import native
        if arr.nbytes:
            if native.available():
                native.buf_copy(arr, 0, raw)
            else:
                np.copyto(arr.reshape(-1).view(np.uint8),
                          np.frombuffer(raw, dtype=np.uint8))
        CH_STATS["rx_raw"] += 1
        CH_STATS["landed_bytes"] += arr.nbytes
        if is_device:
            import jax
            return jax.device_put(arr)
        return arr

    def read(self, timeout: Optional[float] = None) -> Any:
        with _tp.span("channel", f"ch.read:{self.ch.label}",
                      extra={"transport": "wire"}):
            msg = self._next(timeout)
            if RAW_KEY in msg:
                value = self._land_raw(msg)
            else:
                value = pickle.loads(msg["blob"])
                CH_STATS["rx_blob"] += 1
            try:
                self._conn.send({"type": CH_ACK, "name": self.ch.name,
                                 "reader": self.idx,
                                 "seq": int(msg["seq"])})
            except protocol.ConnectionClosed:
                pass               # writer gone: its flow control is moot
            CH_STATS["reads"] += 1
        if RAW_KEY not in msg and msg.get("err"):
            # mirror the shm reader: error frames carry a pickled repr
            shown = value
            if isinstance(shown, (bytes, bytearray)):
                try:
                    shown = pickle.loads(shown)
                except Exception:
                    pass
            raise RuntimeError(f"upstream DAG node failed: {shown}")
        return value

    def read_bytes(self, timeout: Optional[float] = None) -> bytes:
        value = self.read(timeout)
        if not isinstance(value, (bytes, bytearray)):
            raise RuntimeError(
                "read_bytes on a non-bytes wire-channel frame")
        return bytes(value)

    def release(self) -> None:
        try:
            self._conn.close()
        except Exception:
            pass
