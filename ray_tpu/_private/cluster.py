"""Cluster task manager: multi-node placement, PGs, node health.

Parity map (reference src/ray/):
- node selection policies -> raylet/scheduling/policy/
  hybrid_scheduling_policy.h:50 (pack-until-threshold-then-spread),
  spread, node-affinity; bundle policies
  raylet/scheduling/policy/bundle_scheduling_policy.cc.
- placement groups -> gcs/gcs_server GcsPlacementGroupManager/-Scheduler
  2-phase reserve/commit with rollback.
- node lifecycle + health -> GcsNodeManager (gcs_node_manager.h:62) +
  GcsHealthCheckManager (gcs_health_check_manager.h:39): heartbeat
  staleness marks a node dead and triggers task/actor/PG recovery.
- spillback -> ClusterTaskManager::ScheduleOnNode redirect: a task aging
  in one node's queue is handed back and re-placed on a node with room.

Nodes here are in-process Scheduler instances (each owning real worker
subprocesses) — the same-host multi-raylet topology the reference uses
for cluster testing (python/ray/cluster_utils.py:135), which is also the
honest TPU-era model for one driver managing N pod hosts.
"""
from __future__ import annotations

import logging
import threading
import time
import uuid

log = logging.getLogger(__name__)
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.scheduler import Scheduler, fits
from ray_tpu._private.specs import ActorSpec, TaskSpec, bump_attempt
from ray_tpu.exceptions import PlacementGroupUnschedulableError

# PG states (reference rpc::PlacementGroupTableData).
PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"
PG_RESCHEDULING = "RESCHEDULING"

from ray_tpu._private.config import CONFIG as _CFG
_HYBRID_THRESHOLD = 0.5


@dataclass
class NodeRecord:
    node_id: str
    scheduler: Scheduler
    is_head: bool = False
    alive: bool = True
    labels: Dict[str, str] = field(default_factory=dict)
    last_heartbeat: float = field(default_factory=time.monotonic)
    started_at: float = field(default_factory=time.time)
    # Drain-before-kill state (r14 preemption notice): a draining node
    # is alive but receives no new placements; drain_acked flips when
    # every interested party (elastic trainers) has flushed state and
    # the node may be released before its deadline. The deadline itself
    # is enforced by whoever issued the drain (the autoscaler's sweep),
    # not here — the cluster only tracks the routing/ack state.
    draining: bool = False
    drain_acked: bool = False
    # Suspicion state (r17 gray failures): heartbeat older than
    # RAY_TPU_SUSPECT_S but younger than the death timeout. A suspect
    # node is alive — no recovery runs — but routing/rebalance/spill
    # skip it, pulls deprioritize it, and the autoscaler excludes its
    # capacity. The NEXT heartbeat clears the flag inline (recovery is
    # free); recovered_pending defers the RECOVERED event + infeasible
    # retry to the monitor sweep, which may publish/lock — heartbeat()
    # is called from under node locks and must stay lock-free.
    suspect: bool = False
    recovered_pending: bool = False


@dataclass
class PGRecord:
    pg_id: str
    bundles: List[dict]
    strategy: str
    name: str = ""
    state: str = PG_PENDING
    # bundle index -> node_id (filled when reserved)
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    created_at: float = field(default_factory=time.time)


class ClusterTaskManager:
    """Owns the node set; places tasks/actors/bundles onto nodes."""

    def __init__(self, runtime):
        self._rt = runtime
        # With an autoscaler attached, "no node fits" is pending demand
        # (capacity may be provisioned), not a hard error; the
        # Autoscaler flips this (reference: feasibility is judged
        # against node TYPES, not live nodes, when autoscaling).
        self.autoscaling_enabled = False
        self.autoscaler_node_types: List[dict] = []
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock("cluster", reentrant=True)
        self._nodes: Dict[str, NodeRecord] = {}
        self._pgs: Dict[str, PGRecord] = {}
        self._pending_pgs: List[str] = []
        self._infeasible: List = []       # specs no live node can EVER fit
        # r17 membership observability (liveness_stats / metrics);
        # bumped via bump_liveness from the monitor thread AND
        # per-connection reader threads — dict += is a non-atomic
        # read-modify-write, so increments go through one small lock
        self.liveness_counters: Dict[str, int] = {
            "suspected": 0, "recovered": 0, "deaths": 0, "fenced": 0}
        self._counter_lock = threading.Lock()
        # node_id -> rejoin deadline: rehydrated agents expected to
        # re-register after a head restart (reference: raylets reconnect
        # to a restarted GCS; gcs_init_data.cc rehydrated node table)
        self._rejoining: Dict[str, float] = {}
        self._running = True
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="ray-tpu-health", daemon=True)
        self._monitor.start()
        # r10 delegated steal: its own thread — a wedged agent can
        # stall the revoke SEND (socket buffer full, 30s SO_SNDTIMEO),
        # which must never delay the health monitor's death detection
        self._rebalancer = threading.Thread(
            target=self._rebalance_loop, name="ray-tpu-rebalance",
            daemon=True)
        self._rebalancer.start()

    # ------------------------------------------------------------ nodes
    def add_node(self, resources: Dict[str, float],
                 max_workers: Optional[int] = None, is_head: bool = False,
                 labels: Optional[Dict[str, str]] = None) -> NodeRecord:
        node_id = ("head_" if is_head else "node_") + uuid.uuid4().hex[:8]
        sched = Scheduler(self._rt, dict(resources), self._rt.address,
                          max_workers, node_id=node_id, cluster=self)
        rec = NodeRecord(node_id=node_id, scheduler=sched, is_head=is_head,
                         labels=dict(labels or {}))
        with self._lock:
            self._nodes[node_id] = rec
        self._rt.controller.register_node(node_id, resources,
                                          is_head=is_head, labels=labels)
        self._rt.controller.publish_node_event(node_id, "ALIVE")
        sched.start()
        # New capacity: retry anything parked as infeasible + pending PGs.
        self._retry_infeasible()
        self._retry_pending_pgs()
        return rec

    def add_remote_node(self, conn, resources: Dict[str, float],
                        labels: Optional[Dict[str, str]] = None,
                        advertise_addr: Optional[tuple] = None,
                        node_id: Optional[str] = None) -> NodeRecord:
        """A node-agent process registered over TCP (reference
        GcsNodeManager::HandleRegisterNode, gcs_node_manager.h:62). The
        node's scheduler is a RemoteNodeHandle proxy; the real scheduler
        + worker pool run in the agent. The agent mints its own node id
        (its scheduler must exist before the head can route to it)."""
        from ray_tpu._private.remote_node import RemoteNodeHandle
        node_id = node_id or ("node_" + uuid.uuid4().hex[:8])
        ha = getattr(self._rt, "_ha", None)
        proxy = RemoteNodeHandle(node_id, conn, dict(resources),
                                 advertise_addr or ("127.0.0.1", 0),
                                 wal_log=(ha.log if ha is not None
                                          else None))
        rec = NodeRecord(node_id=node_id, scheduler=proxy, is_head=False,
                         labels=dict(labels or {}))
        # r17: every (re)registration earns a fresh incarnation; the
        # runtime stamps it on the agent's connection and frames from
        # older epochs are fenced at the frame-apply points.
        proxy.incarnation = self._rt.controller.mint_incarnation(node_id)
        with self._lock:
            old = self._nodes.get(node_id)
            self._nodes[node_id] = rec
            self._rejoining.pop(node_id, None)   # made it back in time
        if old is not None and old.alive and old.scheduler is not proxy:
            # transient reconnect replacing a live handle: inherit its
            # mirror so in-flight completions still pop their specs,
            # and stop its lease flusher (it would leak a thread)
            try:
                old.scheduler._lease_flusher.stop()
                with old.scheduler._lock:
                    # snapshot under the OLD handle's lock: its reader
                    # thread may still be popping entries for late
                    # completions
                    work = dict(old.scheduler._work)
                    leased = set(old.scheduler._leased)
                proxy.adopt_mirror(work, leased)
            except Exception:
                log.exception("mirror hand-over on reconnect failed")
        self._rt.controller.register_node(node_id, resources,
                                          is_head=False, labels=labels)
        self._rt.controller.publish_node_event(node_id, "ALIVE")
        # Deferred: retries may issue bundle-reserve RPCs on THIS conn,
        # and we are on its reader thread (a blocking request here would
        # deadlock against ourselves).
        threading.Thread(target=self._retry_after_join,
                         name="rtpu-join-retry", daemon=True).start()
        return rec

    def _retry_after_join(self) -> None:
        try:
            self._retry_infeasible()
            self._retry_pending_pgs()
        except Exception:
            pass

    def remove_node(self, node_id: str, graceful: bool = True) -> None:
        """Graceful drain or simulated abrupt node death."""
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive:
                return
        if graceful:
            self._on_node_death(node_id, cause="removed")
        else:
            # Abrupt: kill worker processes without notice and stop the
            # heartbeat; the health monitor must *detect* it (the
            # reference's failure-detection path, not the removal path).
            rec.scheduler.die_silently()

    def nodes(self) -> List[NodeRecord]:
        with self._lock:
            return list(self._nodes.values())

    def alive_nodes(self) -> List[NodeRecord]:
        with self._lock:
            return [n for n in self._nodes.values() if n.alive]

    def schedulable_nodes(self) -> List[NodeRecord]:
        """Alive nodes that accept NEW placements: draining nodes (a
        preemption notice is in flight) and SUSPECT nodes (heartbeat
        stale past RAY_TPU_SUSPECT_S — a gray failure in progress) are
        excluded so nothing fresh lands on a host about to die. A
        suspect node rejoins this set the instant its next heartbeat
        lands (heartbeat() clears the flag inline)."""
        with self._lock:
            return [n for n in self._nodes.values()
                    if n.alive and not n.draining and not n.suspect]

    # ------------------------------------------- drain-before-kill (r14)
    def drain_node(self, node_id: str,
                   deadline_s: Optional[float] = None) -> bool:
        """Preemption-notice drain: stop routing new work to `node_id`,
        reclaim its queued-not-started backlog through the r10 lease-
        revoke machinery and re-place it elsewhere, and publish a
        DRAINING node event (elastic trainers flush a checkpoint on
        it). The node stays ALIVE — the caller terminates it once the
        drain is acknowledged or `deadline_s` lapses; the deadline is
        advisory here (the autoscaler's drain sweep owns the clock).
        Returns False for unknown/dead/head nodes."""
        del deadline_s                       # caller-enforced (see doc)
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive or rec.is_head:
                return False
            if rec.draining:
                return True                  # idempotent re-notice
            rec.draining = True
            rec.drain_acked = False
        try:
            rec.scheduler.set_draining(True)
        except Exception:
            pass
        self._rt.controller.publish_node_event(
            node_id, "DRAINING", cause="preemption notice")
        self._reclaim_draining(rec)
        return True

    def _reclaim_draining(self, rec: NodeRecord) -> None:
        """Pull queued-not-started work off a draining node and
        re-place it. Delegated agents hand specs back via the r10
        lease_reclaimed event (the runtime re-submits them; routing now
        skips the draining node); local schedulers reclaim through
        reclaim_tasks with a resubmit callback. Running tasks stay —
        they either finish inside the drain window or ride the normal
        node-death recovery."""
        h = rec.scheduler
        if getattr(h, "revoke_lease", None) is not None:
            # remote agent: reclaim through NODE_LEASE_REVOKE whenever
            # the peer SPEAKS the op (wire MINOR >= 3) — delegation
            # off still mirrors pushed specs in _work and the agent's
            # revoke handler works in either lease mode. An older peer
            # cannot reclaim; its queued work rides the death path.
            if h.conn.peer_speaks_delegate():
                ids = h.queued_task_ids(limit=4096)
                if ids:
                    h.revoke_lease(ids)
            return
        if not hasattr(h, "reclaim_tasks"):
            return
        ids = h.queued_task_ids()
        if not ids:
            return

        def _resubmit(specs):
            for spec in specs:
                try:
                    bump_attempt(spec)
                    self.submit(spec)
                except Exception:
                    log.exception("drain resubmit failed")

        h.reclaim_tasks(ids, _resubmit)

    def acknowledge_drain(self, node_id: str) -> None:
        """A drain listener (elastic trainer) flushed its state: the
        node may be released before its deadline. Publishes DRAINED so
        the autoscaler's next sweep (or an external provider loop) can
        terminate immediately."""
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.draining or rec.drain_acked:
                return
            rec.drain_acked = True
        self._rt.controller.publish_node_event(node_id, "DRAINED")

    def is_draining(self, node_id: str) -> bool:
        with self._lock:
            rec = self._nodes.get(node_id)
            return bool(rec is not None and rec.alive and rec.draining)

    def alive_node_count(self) -> int:
        """LOCK-FREE alive-node count (single atomic dict scan): safe to
        call while holding a node lock, where taking the cluster lock
        would ABBA-deadlock against cluster->node lock paths."""
        return sum(1 for n in list(self._nodes.values()) if n.alive)

    def get_node(self, node_id: str) -> Optional[NodeRecord]:
        with self._lock:
            return self._nodes.get(node_id)

    def heartbeat(self, node_id: str) -> None:
        # Lock-free by contract: local schedulers call this from under
        # their own node lock every dispatch tick. Clearing suspicion
        # here is what makes blip recovery FREE — the node is
        # schedulable again before the monitor's next 0.5 s sweep; the
        # sweep only publishes the deferred RECOVERED event.
        rec = self._nodes.get(node_id)
        if rec is not None:
            rec.last_heartbeat = time.monotonic()
            if rec.suspect:
                rec.suspect = False
                rec.recovered_pending = True

    def bump_liveness(self, key: str, n: int = 1) -> None:
        with self._counter_lock:
            self.liveness_counters[key] = \
                self.liveness_counters.get(key, 0) + n

    def is_suspect(self, node_id: str) -> bool:
        rec = self._nodes.get(node_id)
        return bool(rec is not None and rec.alive and rec.suspect)

    def liveness_stats(self) -> dict:
        """Per-node liveness view + transition counters (r17): the
        `liveness_stats` state op and the /metrics liveness gauges
        read this."""
        now = time.monotonic()
        with self._lock:
            nodes = [{
                "node_id": n.node_id,
                "is_head": n.is_head,
                "state": ("dead" if not n.alive
                          else "suspect" if n.suspect
                          else "draining" if n.draining
                          else "alive"),
                "last_heartbeat_age_s": round(now - n.last_heartbeat, 3),
            } for n in self._nodes.values()]
        with self._counter_lock:
            counters = dict(self.liveness_counters)
        return {"nodes": nodes, "counters": counters}

    def total_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.scheduler.total.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.scheduler.avail.items():
                out[k] = out.get(k, 0.0) + v
        return out

    # ------------------------------------------------- worker routing
    def scheduler_for_worker(self, worker_id: str) -> Optional[Scheduler]:
        # Snapshot under the cluster lock, probe AFTER releasing it:
        # owns_worker takes the node's scheduler lock, and dispatch paths
        # hold that lock while calling back into cluster methods — probing
        # lock-held is a cluster->scheduler / scheduler->cluster ABBA
        # (flagged by the RAY_TPU_DEBUG_LOCKS order detector).
        with self._lock:
            nodes = list(self._nodes.values())
        for n in nodes:
            if n.scheduler.owns_worker(worker_id):
                return n.scheduler
        return None

    def scheduler_for_node(self, node_id: str) -> Optional[Scheduler]:
        rec = self.get_node(node_id)
        return rec.scheduler if rec else None

    # -------------------------------------------------------- placement
    def submit(self, spec) -> None:
        """Route a TaskSpec/ActorSpec to a node queue (two-stage
        scheduling, stage 1: ClusterTaskManager::QueueAndScheduleTask)."""
        affinity = getattr(spec, "node_id", None)
        if affinity:
            rec = self.get_node(affinity)
            if rec is None or not rec.alive:
                if getattr(spec, "affinity_soft", False):
                    spec.node_id = None  # soft: fall back anywhere
                else:
                    # Hard affinity to a dead node fails immediately
                    # (reference NodeAffinitySchedulingStrategy
                    # soft=False semantics) instead of hanging.
                    self._rt.on_unplaceable(
                        spec, f"node {affinity} is dead or unknown")
                    return
        node = self._select_node(spec)
        if node is None:
            pg_id = getattr(spec, "placement_group_id", None)
            if pg_id:
                pg = self._pgs.get(pg_id)
                if pg is None or pg.state == PG_REMOVED:
                    self._rt.on_unplaceable(
                        spec, f"placement group {pg_id} does not exist "
                        f"or was removed")
                    return
                # PG pending/rescheduling: park until bundles reserve.
                with self._lock:
                    self._infeasible.append(spec)
                return
            with self._lock:
                self._infeasible.append(spec)
            import sys
            sys.stderr.write(
                f"ray_tpu: no node can ever satisfy resources "
                f"{getattr(spec, 'resources', {})} for "
                f"{getattr(spec, 'name', spec)} — task will hang until a "
                f"node with capacity joins\n")
            return
        node.scheduler.enqueue(spec)

    def try_spill(self, spec, from_node_id: str) -> bool:
        """Stage-1 re-placement for a task aging in a node queue.

        Returns True if the spec was moved to another node."""
        if getattr(spec, "node_id", None) or getattr(
                spec, "placement_group_id", None):
            return False                  # constrained: cannot move
        constraints = getattr(spec, "label_constraints", None)
        need = Scheduler.need_of(spec)
        best = None
        for n in self.schedulable_nodes():
            if n.node_id == from_node_id:
                continue
            if constraints is not None:
                from ray_tpu.util.scheduling_strategies import \
                    labels_match
                if not labels_match(n.labels, constraints[0]):
                    continue
            if fits(n.scheduler.effective_avail(), need):
                best = n
                break
        if best is None:
            return False
        best.scheduler.enqueue(spec)
        return True

    def _select_node(self, spec) -> Optional[NodeRecord]:
        """Hybrid policy (hybrid_scheduling_policy.h:50): walk nodes in
        creation order packing onto any node under the utilization
        threshold that fits; else least-utilized feasible node; honours
        node-affinity and PG bundle locations first."""
        affinity = getattr(spec, "node_id", None)
        pg_id = getattr(spec, "placement_group_id", None)
        # Draining nodes take nothing new; explicit affinity/PG-bundle
        # placements below still resolve (the user pinned them there).
        nodes = self.schedulable_nodes()
        if affinity:
            rec = self.get_node(affinity)
            return rec if rec is not None and rec.alive else None
        if pg_id:
            pg = self._pgs.get(pg_id)
            if pg is None or pg.state == PG_REMOVED:
                return None
            idx = getattr(spec, "placement_group_bundle_index", -1)
            candidates = (pg.bundle_nodes if idx in (-1, None)
                          else [pg.bundle_nodes[idx]])
            for nid in candidates:
                rec = self.get_node(nid) if nid else None
                if rec is not None and rec.alive:
                    return rec
            return None
        need = Scheduler.need_of(spec)
        feasible = [n for n in nodes if fits(n.scheduler.total, need)]
        constraints = getattr(spec, "label_constraints", None)
        if constraints is not None:
            # node-label scheduling (reference
            # NodeLabelSchedulingStrategy): hard constraints filter,
            # soft constraints prefer among the survivors
            from ray_tpu.util.scheduling_strategies import labels_match
            hard, soft = constraints
            feasible = [n for n in feasible
                        if labels_match(n.labels, hard)]
            if soft:
                preferred = [n for n in feasible
                             if labels_match(n.labels, soft)]
                if preferred:
                    feasible = preferred
        if not feasible:
            return None
        # AT MOST one effective_avail snapshot (= one scheduler-lock
        # round trip) per node per selection, taken lazily: the
        # pack/spread phases below previously re-took that hot lock
        # 3-5x per submit, serializing submission against dispatch/
        # completion processing — a large share of per-submit head CPU
        # under a drain (r7 profile). Lazy, so the common case (first
        # node passes the pack check) still touches one node.
        eff_cache: dict = {}
        util_cache: dict = {}

        def _eff(n):
            e = eff_cache.get(id(n))
            if e is None:
                e = eff_cache[id(n)] = n.scheduler.effective_avail()
            return e

        def _util(n):
            u = util_cache.get(id(n))
            if u is None:
                u = util_cache[id(n)] = Scheduler.utilization_from(
                    _eff(n), n.scheduler.total)
            return u

        # Locality phase (reference locality-aware hybrid policy:
        # scheduling prefers nodes already holding the task's argument
        # bytes): consult the cluster object directory for where the
        # spec's pinned refs live, and take the best-scoring feasible
        # node if it can run the task NOW. Directory misses (inline
        # args, single-node, head-resident objects) cost one empty-dict
        # check.
        pinned = getattr(spec, "pinned_refs", None)
        if pinned and _CFG.scheduler_locality:
            ctrl = getattr(self._rt, "controller", None)
            directory = getattr(ctrl, "directory", None) if ctrl else None
            if directory is not None and not directory.empty():
                scores = directory.locality_bytes(
                    pinned, [n.node_id for n in feasible])
                if scores:
                    local = [n for n in feasible
                             if scores.get(n.node_id)]
                    local.sort(key=lambda n: -scores[n.node_id])
                    for n in local:
                        if fits(_eff(n), need):
                            return n

        # Pack phase: first node (stable order) with enough room now and
        # below the utilization threshold (both incl. queued demand).
        for n in feasible:
            if _util(n) < _HYBRID_THRESHOLD and fits(_eff(n), need):
                return n
        # Spread phase: least-utilized node that fits now.
        fitting = [n for n in feasible if fits(_eff(n), need)]
        if fitting:
            return min(fitting, key=_util)
        # Nothing fits *now*: queue on the least-utilized feasible node;
        # its dispatch loop waits for resources (or spills back later).
        return min(feasible, key=_util)

    def _retry_infeasible(self) -> None:
        with self._lock:
            specs, self._infeasible = self._infeasible, []
        for spec in specs:
            self.submit(spec)

    # ------------------------------------------------- placement groups
    def create_pg(self, bundles: List[dict], strategy: str,
                  name: str = "") -> PGRecord:
        if strategy not in ("PACK", "SPREAD", "STRICT_PACK",
                            "STRICT_SPREAD"):
            raise ValueError(f"unknown placement strategy {strategy!r}")
        if not bundles:
            raise ValueError("placement group needs at least one bundle")
        for b in bundles:
            if not b or any(v < 0 for v in b.values()):
                raise ValueError(f"invalid bundle {b!r}")
        pg = PGRecord(pg_id="pg_" + uuid.uuid4().hex[:8],
                      bundles=[dict(b) for b in bundles],
                      strategy=strategy, name=name,
                      bundle_nodes=[None] * len(bundles))
        self._check_feasible_ever(pg)
        with self._lock:
            self._pgs[pg.pg_id] = pg
        if not self._try_reserve(pg):
            with self._lock:
                self._pending_pgs.append(pg.pg_id)
        self._rt.controller.register_pg_view(self.pg_table_entry(pg))
        return pg

    def _check_feasible_ever(self, pg: PGRecord) -> None:
        """Raise if no future availability could ever satisfy the PG
        (VERDICT r1: unschedulable must raise, not silently ignore).
        Under autoscaling, feasibility is judged against the
        autoscaler's node TYPES (capacity can appear) instead of live
        nodes."""
        if self.autoscaling_enabled:
            types = self.autoscaler_node_types
            if types:
                for b in pg.bundles:
                    if not any(fits(t, b) for t in types):
                        raise PlacementGroupUnschedulableError(
                            f"no autoscaler node type can fit bundle "
                            f"{b} (types: {types})")
            return
        nodes = self.alive_nodes()
        if pg.strategy == "STRICT_SPREAD":
            if len(pg.bundles) > len(nodes):
                raise PlacementGroupUnschedulableError(
                    f"STRICT_SPREAD needs {len(pg.bundles)} nodes, "
                    f"cluster has {len(nodes)}")
            unplaced = [b for b in pg.bundles
                        if not any(fits(n.scheduler.total, b)
                                   for n in nodes)]
            if unplaced:
                raise PlacementGroupUnschedulableError(
                    f"no node can fit bundle {unplaced[0]}")
        elif pg.strategy == "STRICT_PACK":
            merged: Dict[str, float] = {}
            for b in pg.bundles:
                for k, v in b.items():
                    merged[k] = merged.get(k, 0.0) + v
            if not any(fits(n.scheduler.total, merged) for n in nodes):
                raise PlacementGroupUnschedulableError(
                    f"no single node can fit STRICT_PACK total {merged}")
        else:
            for b in pg.bundles:
                if not any(fits(n.scheduler.total, b) for n in nodes):
                    raise PlacementGroupUnschedulableError(
                        f"no node can ever fit bundle {b}")

    def _try_reserve(self, pg: PGRecord) -> bool:
        """2-phase: plan an assignment against current availability,
        reserve each bundle, roll back all on any failure."""
        plan = self._plan_bundles(pg)
        if plan is None:
            return False
        reserved: List[Tuple[str, int]] = []
        for idx, node_id in enumerate(plan):
            sched = self.scheduler_for_node(node_id)
            if sched is None or not sched.reserve_bundle(
                    pg.pg_id, idx, pg.bundles[idx]):
                for nid, i in reserved:      # rollback
                    s = self.scheduler_for_node(nid)
                    if s is not None:
                        s.release_bundle(pg.pg_id, i)
                return False
            reserved.append((node_id, idx))
        pg.bundle_nodes = list(plan)
        pg.state = PG_CREATED
        self._rt.controller.register_pg_view(self.pg_table_entry(pg))
        return True

    def _plan_bundles(self, pg: PGRecord) -> Optional[List[str]]:
        nodes = self.schedulable_nodes()
        if not nodes:
            return None
        # Work on copies of availability so the plan is consistent.
        avail = {n.node_id: dict(n.scheduler.avail) for n in nodes}
        order = [n.node_id for n in nodes]

        def take(nid, b):
            for k, v in b.items():
                avail[nid][k] = avail[nid].get(k, 0.0) - v

        plan: List[Optional[str]] = [None] * len(pg.bundles)
        if pg.strategy == "STRICT_PACK":
            for nid in order:
                trial = dict(avail[nid])
                ok = True
                for b in pg.bundles:
                    if not fits(trial, b):
                        ok = False
                        break
                    for k, v in b.items():
                        trial[k] = trial.get(k, 0.0) - v
                if ok:
                    return [nid] * len(pg.bundles)
            return None
        if pg.strategy == "STRICT_SPREAD":
            used: set = set()
            for idx, b in enumerate(pg.bundles):
                placed = False
                for nid in order:
                    if nid in used or not fits(avail[nid], b):
                        continue
                    plan[idx] = nid
                    used.add(nid)
                    placed = True
                    break
                if not placed:
                    return None
            return plan  # type: ignore[return-value]
        if pg.strategy == "SPREAD":
            # Round-robin best effort across nodes.
            i = 0
            for idx, b in enumerate(pg.bundles):
                placed = False
                for off in range(len(order)):
                    nid = order[(i + off) % len(order)]
                    if fits(avail[nid], b):
                        plan[idx] = nid
                        take(nid, b)
                        i = (i + off + 1) % len(order)
                        placed = True
                        break
                if not placed:
                    return None
            return plan  # type: ignore[return-value]
        # PACK: fill nodes in order, overflow to the next.
        for idx, b in enumerate(pg.bundles):
            placed = False
            for nid in order:
                if fits(avail[nid], b):
                    plan[idx] = nid
                    take(nid, b)
                    placed = True
                    break
            if not placed:
                return None
        return plan  # type: ignore[return-value]

    def _retry_pending_pgs(self) -> None:
        with self._lock:
            pending, self._pending_pgs = self._pending_pgs, []
        reserved_any = False
        for pg_id in pending:
            pg = self._pgs.get(pg_id)
            if pg is None or pg.state in (PG_CREATED, PG_REMOVED):
                continue
            if self._try_reserve(pg):
                reserved_any = True
            else:
                with self._lock:
                    self._pending_pgs.append(pg_id)
        if reserved_any:
            self._retry_infeasible()   # tasks parked on pending PGs

    def remove_pg(self, pg_id: str) -> None:
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg.state == PG_REMOVED:
                return
            pg.state = PG_REMOVED
            if pg_id in self._pending_pgs:
                self._pending_pgs.remove(pg_id)
        for idx, nid in enumerate(pg.bundle_nodes):
            if nid is None:
                continue
            sched = self.scheduler_for_node(nid)
            if sched is not None:
                sched.release_bundle(pg_id, idx)
        self._rt.controller.register_pg_view(self.pg_table_entry(pg))

    def get_pg(self, pg_id: str) -> Optional[PGRecord]:
        with self._lock:
            return self._pgs.get(pg_id)

    def wait_pg(self, pg_id: str, timeout: Optional[float]) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            pg = self.get_pg(pg_id)
            if pg is None or pg.state == PG_REMOVED:
                return False
            if pg.state == PG_CREATED:
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            self._retry_pending_pgs()
            time.sleep(0.05)

    def pg_table_entry(self, pg: PGRecord) -> dict:
        return {"placement_group_id": pg.pg_id, "state": pg.state,
                "bundles": pg.bundles, "strategy": pg.strategy,
                "name": pg.name, "bundle_nodes": list(pg.bundle_nodes)}

    def fail_type_infeasible(self, type_fits) -> None:
        """Fail parked tasks whose shape NO autoscaler node type can
        satisfy (they would otherwise wait forever; reference
        autoscaler surfaces these as infeasible-request errors)."""
        with self._lock:
            doomed = [s for s in self._infeasible
                      if not type_fits(dict(getattr(s, "resources", None)
                                            or {"CPU": 1.0}))]
            for s in doomed:
                self._infeasible.remove(s)
        for s in doomed:
            self._rt.on_unplaceable(
                s, "no autoscaler node type can satisfy "
                   f"{getattr(s, 'resources', None)}")

    def cancel_parked(self, task_id: str):
        """Remove + return a task parked as infeasible (cancel path:
        parked tasks are in NO node queue, so node-level cancel misses
        them)."""
        with self._lock:
            for spec in list(self._infeasible):
                if getattr(spec, "task_id", None) == task_id:
                    self._infeasible.remove(spec)
                    return spec
        return None

    def pg_table(self) -> List[dict]:
        with self._lock:
            return [self.pg_table_entry(pg) for pg in self._pgs.values()]

    # --------------------------------------------- head-restart rejoin
    def expect_rejoin(self, node_id: str, grace_s: float) -> None:
        """A rehydrated node gets `grace_s` to re-register before its
        actors/objects are recovered as dead."""
        with self._lock:
            self._rejoining[node_id] = time.monotonic() + grace_s

    def restore_pgs(self, entries: List[dict]) -> None:
        """Rebuild PG records from rehydrated controller views. Bundle
        reservations live agent-side and survive the head restart; a
        node that never rejoins triggers rescheduling via
        _fail_rejoining_node."""
        with self._lock:
            for e in entries:
                pg = PGRecord(
                    pg_id=e["placement_group_id"],
                    bundles=[dict(b) for b in e["bundles"]],
                    strategy=e["strategy"], name=e.get("name", ""),
                    state=e["state"],
                    bundle_nodes=list(e.get("bundle_nodes",
                                            [None] * len(e["bundles"]))))
                self._pgs[pg.pg_id] = pg
                if pg.state in (PG_PENDING, PG_RESCHEDULING):
                    self._pending_pgs.append(pg.pg_id)

    def _fail_rejoining_node(self, node_id: str) -> None:
        """A rehydrated node missed its rejoin deadline: run the
        node-death recovery that _on_node_death would have (there is no
        NodeRecord/scheduler to drain — the head that owned it died)."""
        with self._lock:
            if node_id in self._nodes:
                # the agent's registration raced the deadline sweep and
                # won: it is alive — do not recover (duplicate) actors
                return
        self._rt.controller.set_node_state(
            node_id, alive=False, cause="did not rejoin after head restart")
        self._rt.controller.publish_node_event(
            node_id, "DEAD", cause="did not rejoin after head restart")
        # r15: the node's rehydrated spec mirror was parked awaiting its
        # rejoin — its workers died with the old head's cluster, so
        # every mirrored plain task re-places exactly once (the r10
        # agent-death resubmit semantics, driven from persisted state)
        ha = getattr(self._rt, "_ha", None)
        pend = ha.take_pending_node(node_id) if ha is not None else None
        self._rt.controller.bump_incarnation(node_id)
        if pend is not None:
            for key, (spec, _dispatched) in pend.work.items():
                if isinstance(spec, TaskSpec):
                    self._rt.controller.record_task_event(
                        spec.task_id, spec.name, "RESUBMITTED",
                        error=f"node {node_id} did not rejoin after "
                              f"head restart")
                    try:
                        bump_attempt(spec)
                        self.submit(spec)
                    except Exception:
                        log.exception("rejoin-expiry resubmit failed")
        for actor_id in self._rt.controller.actors_on_node(node_id):
            self._rt._recover_actor(actor_id)
        if hasattr(self._rt, "on_node_objects_lost"):
            self._rt.on_node_objects_lost(node_id)
        self._reschedule_pgs_for(node_id)

    def _reschedule_pgs_for(self, node_id: str) -> None:
        """Bundles reserved on a dead node go back to pending and try to
        re-reserve elsewhere (GcsPlacementGroupManager rescheduling)."""
        with self._lock:
            hit = [pg for pg in self._pgs.values()
                   if pg.state == PG_CREATED and node_id in pg.bundle_nodes]
        for pg in hit:
            for idx, nid in enumerate(pg.bundle_nodes):
                if nid is not None and nid != node_id:
                    sched = self.scheduler_for_node(nid)
                    if sched is not None:
                        sched.release_bundle(pg.pg_id, idx)
            pg.bundle_nodes = [None] * len(pg.bundles)
            pg.state = PG_RESCHEDULING
            if not self._try_reserve(pg):
                with self._lock:
                    self._pending_pgs.append(pg.pg_id)

    # ------------------------------------------- delegated steal (r10)
    def _rebalance_loop(self) -> None:
        """Stage-1 spillback for DELEGATED agents: local queues spill
        themselves (`Scheduler._spill_aged_locked`), but an agent runs
        with cluster=None and its bulk-leased backlog is invisible to
        any local spill scan — so the head, which still owns every
        leased spec, periodically revokes queued-not-started work from
        an agent reporting unmet demand and re-places it on a node
        with room (reference ClusterTaskManager::ScheduleOnNode
        redirect, applied to leases)."""
        while self._running:
            time.sleep(1.0)
            try:
                self._rebalance_once()
            except Exception:
                log.exception("delegated rebalance sweep failed")

    def _rebalance_once(self) -> None:
        nodes = self.alive_nodes()
        if len(nodes) < 2:
            return
        for n in nodes:
            h = n.scheduler
            if (getattr(h, "revoke_lease", None) is None
                    or not h.delegates()):
                continue            # local node / pre-delegation agent
            shapes = h.pending_shapes()
            if not shapes:
                continue            # no unmet demand: nothing stuck
            if not any(fits(m.scheduler.effective_avail(), shapes[0])
                       for m in nodes
                       if m is not n and m.alive and not m.draining
                       and not m.suspect):
                continue            # nowhere better: leave the lease
            ids = h.steal_candidates()
            if ids:
                # fire-and-forget: the agent's lease_reclaimed event
                # hands the specs back and the runtime re-places them
                # (spill-count-capped there) — no blocking reply to
                # stall this sweep against a wedged agent
                h.revoke_lease(ids)

    # ----------------------------------------------------- node failure
    def _monitor_loop(self) -> None:
        """GcsHealthCheckManager parity: staleness-based liveness."""
        while self._running:
            time.sleep(0.5)
            try:
                self._sweep_liveness()
            except Exception:
                log.exception("liveness sweep failed")

    def _sweep_liveness(self) -> None:
        """One liveness pass (r17: alive -> SUSPECT -> dead instead of
        alive -> dead). Separated from the loop so tests drive
        deterministic transitions. SUSPECT is pure routing state — no
        recovery runs, which is the whole point: a blip shorter than
        the death timeout costs scheduling preference, not a node-
        death recovery (and heartbeat() clears it for free)."""
        now = time.monotonic()
        suspect_s = _CFG.suspect_s
        dead_s = _CFG.heartbeat_timeout_s
        if suspect_s >= dead_s > 0:
            # the documented constraint is suspect_s < timeout; an
            # operator lowering the death timeout alone would
            # otherwise silently lose the whole suspect state (the
            # death branch always wins) — clamp and say so once
            if not getattr(self, "_suspect_clamp_warned", False):
                self._suspect_clamp_warned = True
                log.warning(
                    "RAY_TPU_SUSPECT_S (%.2fs) >= heartbeat_timeout_s "
                    "(%.2fs); clamping suspicion to %.2fs", suspect_s,
                    dead_s, dead_s / 2.0)
            suspect_s = dead_s / 2.0
        dead = []
        expired = []
        suspected = []
        recovered = []
        with self._lock:
            for n in self._nodes.values():
                if not n.alive:
                    # death already superseded any pending recovery
                    # event (never publish RECOVERED after DEAD)
                    n.recovered_pending = False
                    continue
                if n.recovered_pending:
                    n.recovered_pending = False
                    recovered.append(n.node_id)
                age = now - n.last_heartbeat
                if age > dead_s:
                    dead.append(n.node_id)
                elif (suspect_s > 0 and not n.suspect and not n.is_head
                        and age > suspect_s):
                    n.suspect = True
                    # heartbeat() is lock-free by contract and may
                    # have landed between our age read and the flag
                    # set: re-check so a fresh beat is never wrongly
                    # suspected for a whole sweep period
                    if now - n.last_heartbeat <= suspect_s:
                        n.suspect = False
                        n.recovered_pending = False
                    else:
                        suspected.append(n.node_id)
            for nid, deadline in list(self._rejoining.items()):
                if now > deadline:
                    self._rejoining.pop(nid)
                    expired.append(nid)
        for nid in suspected:
            self.bump_liveness("suspected")
            self._rt.controller.publish_node_event(
                nid, "SUSPECT", cause="heartbeat stale")
        for nid in recovered:
            self.bump_liveness("recovered")
            self._rt.controller.publish_node_event(
                nid, "RECOVERED", cause="heartbeat resumed")
        if recovered:
            # a blip may have parked fresh submissions as infeasible
            # (every capable node was suspect): re-place them now
            self._retry_infeasible()
        for nid in dead:
            self._on_node_death(nid, cause="heartbeat timeout")
        for nid in expired:
            try:
                self._fail_rejoining_node(nid)
            except Exception:
                # the node was already popped from _rejoining, so
                # this recovery will not re-run — never lose it
                # silently
                log.exception("rejoin-expiry recovery for %s failed",
                              nid)

    def _on_node_death(self, node_id: str, cause: str) -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is None or not rec.alive:
                return
            rec.alive = False
            rec.suspect = False
            rec.recovered_pending = False
            self._rt.controller.publish_node_event(node_id, "DEAD",
                                                   cause=cause)
        self.bump_liveness("deaths")
        self._rt.controller.set_node_state(node_id, alive=False,
                                           cause=cause)
        # 0. Fence the incarnation BEFORE any re-placement (r17): the
        #    node may be a partitioned/stalled zombie, not a corpse —
        #    from here on, frames still arriving under its old epoch
        #    are dropped and answered with NODE_FENCED, so nothing the
        #    zombie produces can race the recovery below.
        self._rt.controller.bump_incarnation(node_id)
        # 1. Tear down the node's workers; collect its queue + running
        #    work. A death declared by HEARTBEAT STALENESS keeps the
        #    agent's control connection open (a partition delivers no
        #    FIN either): if the node is actually alive, its next
        #    frame on that connection earns the NODE_FENCED answer
        #    that tells it to reset and re-register — closing the
        #    socket here would instead surface as a clean reconnect
        #    and hide the split-brain.
        keep_conn = (cause == "heartbeat timeout"
                     and getattr(rec.scheduler, "conn", None) is not None)
        if keep_conn:
            queued, running_tasks, actor_ids = \
                rec.scheduler.drain_for_death(close_conn=False)
            # Bounded fencing window: if the node really is dead (no
            # process left to ever close its end), the kept socket
            # would leak for the head's lifetime — reap it once the
            # window lapses and no NEW registration replaced it. A
            # partition outlasting the window still recovers: the
            # agent sees the close on heal and rejoins, where the
            # fresh incarnation + drained-mirror dedup give the same
            # exactly-once outcome as the fence path.
            old_conn = rec.scheduler.conn
            window = max(10.0, 3.0 * _CFG.heartbeat_timeout_s)

            def _reap(conn=old_conn):
                # idempotent: a fenced agent already closed its side,
                # and an ACTIVE chaos partition defers this close just
                # like any other (the relay keeps test semantics)
                try:
                    conn.close()
                except Exception:
                    pass

            t = threading.Timer(window, _reap)
            t.daemon = True
            t.start()
        else:
            queued, running_tasks, actor_ids = \
                rec.scheduler.drain_for_death()
        # 2. Re-place queued work (attempt bumped: a zombie's terminal
        #    event for the old attempt must lose to the re-placed
        #    winner, first-terminal-wins).
        for spec in queued:
            bump_attempt(spec)
            self.submit(spec)
        # 3. Recover running tasks and actors through the runtime's
        #    existing retry/restart machinery.
        for task in running_tasks:
            self._rt._recover_task(task)
        for actor_id in actor_ids:
            self._rt._recover_actor(actor_id)
        # 3b. Objects whose only copy lived on the dead node: lineage
        #     reconstruction (ResubmitTask parity).
        if hasattr(self._rt, "on_node_objects_lost"):
            self._rt.on_node_objects_lost(node_id)
        # 4. PG bundles reserved on the dead node go back to pending and
        #    try to re-reserve elsewhere (GcsPlacementGroupManager
        #    rescheduling path).
        self._reschedule_pgs_for(node_id)

    # -------------------------------------------------------- lifecycle
    def stats(self) -> dict:
        return {
            "nodes": [{
                "node_id": n.node_id, "alive": n.alive,
                "is_head": n.is_head,
                "draining": n.draining,
                "suspect": n.suspect,
                "resources_total": dict(n.scheduler.total),
                "resources_available": dict(n.scheduler.avail),
                "labels": n.labels,
            } for n in self.nodes()],
            "num_placement_groups": len(self._pgs),
            "infeasible_tasks": len(self._infeasible),
        }

    def shutdown(self) -> None:
        self._running = False
        for n in self.nodes():
            n.scheduler.shutdown()
