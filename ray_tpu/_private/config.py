"""Central runtime config registry with env-var override.

Parity: reference src/ray/common/ray_config_def.h (219 RAY_CONFIG
entries, each overridable via a RAY_<name> env var, materialised into a
RayConfig singleton) — scaled to this runtime's knob set. Every entry
is overridable via ``RAY_TPU_<NAME>`` (upper-cased) read at first
access; ``CONFIG.reload()`` re-reads the environment (tests).

Usage::

    from ray_tpu._private.config import CONFIG
    timeout = CONFIG.heartbeat_timeout_s

Adding a knob: one ``_define`` line here — call sites never hardcode.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict


@dataclasses.dataclass(frozen=True)
class ConfigEntry:
    name: str
    default: Any
    parse: Callable[[str], Any]
    doc: str


_REGISTRY: Dict[str, ConfigEntry] = {}


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


def _define(name: str, default: Any, doc: str) -> None:
    parse: Callable[[str], Any]
    if isinstance(default, bool):
        parse = _parse_bool
    elif isinstance(default, int):
        parse = int
    elif isinstance(default, float):
        parse = float
    else:
        parse = str
    _REGISTRY[name] = ConfigEntry(name, default, parse, doc)


# ---------------------------------------------------------------- knobs
_define("heartbeat_timeout_s", 3.0,
        "Node declared dead after this long without a heartbeat "
        "(reference gcs_health_check_manager period*threshold).")
_define("suspect_s", 1.5,
        "Suspicion threshold for gray failures (r17): a node whose "
        "last heartbeat is older than this (but younger than "
        "heartbeat_timeout_s) enters SUSPECT — routing, rebalance, "
        "spillback, and PG planning skip it, the pull manager "
        "deprioritizes it as a source, and the autoscaler excludes "
        "its capacity — but NO recovery runs, so the next heartbeat "
        "restores it for free (a 2 s blip costs routing preference, "
        "not a node-death recovery). Must be < heartbeat_timeout_s; "
        "0 disables the suspect state.")
_define("chaos", False,
        "Enable the protocol-level network fault-injection layer "
        "(r17): tests/chaos.py can then partition, blackhole, slow, "
        "or probabilistically drop frames per connection pair under "
        "seeded rules (both wire engines). Off (default) the layer "
        "is never constructed and the wire behavior is byte-"
        "identical to a build without it. NEVER enable in "
        "production.")
_define("chaos_seed", 0,
        "Seed for the chaos layer's probabilistic frame-drop rules, "
        "so a failing chaos run replays deterministically.")
_define("reconnect_backoff_base_s", 0.25,
        "Initial delay between an agent's head-redial attempts after "
        "a lost head connection; doubles per failure (jittered "
        "+/-50%) up to reconnect_backoff_cap_s instead of hammering "
        "the dead address at a fixed rate.")
_define("reconnect_backoff_cap_s", 2.0,
        "Ceiling on the agent's jittered exponential reconnect "
        "backoff.")
_define("spill_delay_s", 1.0,
        "Queued-task age before the scheduler offers it back to the "
        "cluster for spillback to another node.")
_define("worker_spawn_timeout_s", 60.0,
        "Worker process must register within this long or its spawn "
        "slot is reaped.")
_define("inline_threshold_bytes", 100 * 1024,
        "Buffers below this size ride inline in the pickle stream; "
        "larger ones get their own shm segment (reference plasma "
        "promotion threshold semantics).")
_define("object_store_memory", 0,
        "Object store residency cap in bytes; 0 = unbounded. Past the "
        "cap, LRU unpinned objects spill to disk.")
_define("node_memory_bytes", 8 * 1024 ** 3,
        "Schedulable 'memory' resource reported per node.")
_define("worker_pool_max", 0,
        "Reusable task-worker pool soft cap; 0 = max(2*CPU, 8). Actor-"
        "pinned workers are dedicated processes outside the cap.")
_define("task_event_history", 10_000,
        "Bounded task-event history length in the controller.")
_define("remote_inline_max_bytes", 64 * 1024,
        "Task results at or below this size are forwarded inline from a "
        "node agent to the head (owner-inline parity, reference "
        "core_worker.h AllocateReturnObject); larger results stay in "
        "the agent's store and register a location.")
_define("auth_token", "",
        "Shared secret for listener authentication. When set, every "
        "accepted connection must present it (raw first frame, "
        "constant-time compare) BEFORE any message is deserialized; "
        "workers/agents inherit it via the environment. Strongly "
        "recommended with bind_host=0.0.0.0 — the wire is pickle.")
_define("bind_host", "127.0.0.1",
        "Head listener bind host. Set 0.0.0.0 (or a NIC address) to "
        "accept remote node agents; loopback by default.")
_define("port", 0,
        "Head listener port; 0 picks an ephemeral port.")
_define("lineage_max_resubmits", 3,
        "Cap on per-task lineage re-executions when a node death "
        "orphans a still-referenced object (reference task_manager "
        "ResubmitTask bookkeeping).")
_define("head_snapshot_path", "",
        "When set, the head periodically snapshots all controller "
        "tables (actors, nodes, PGs, KV, lineage, object directory) to "
        "this file and REHYDRATES from it on restart (reference GCS "
        "persistence: gcs_init_data.cc + redis_store_client.h). Empty "
        "disables head fault tolerance.")
_define("head_snapshot_period_s", 1.0,
        "Controller snapshot period when head_snapshot_path is set and "
        "the WAL is disabled (RAY_TPU_HEAD_WAL=0). With the WAL on, "
        "snapshots are taken by compaction instead of on a timer.")
_define("head_wal", True,
        "Write-ahead-log head state changes (r15) when "
        "head_snapshot_path is set: task submit/terminal, lease "
        "grants, mirror routing, refcount/pin batches, directory and "
        "KV/actor/node/PG transitions are group-commit fsynced so a "
        "restarted head rehydrates to the exact pre-crash frontier "
        "(snapshot + WAL tail) instead of the last 1 Hz snapshot. "
        "0 reverts to snapshot-only persistence.")
_define("head_wal_path", "",
        "Head WAL file path; empty defaults to "
        "<head_snapshot_path>.wal.")
_define("head_wal_fsync_ms", 5.0,
        "Group-commit window: records buffered within it share one "
        "write+fsync (the WAL's per-event durability cost is a list "
        "append). 0 fsyncs every flush pass immediately.")
_define("head_wal_compact_bytes", 8 * 1024 * 1024,
        "Active WAL segment size that triggers snapshot+truncate "
        "compaction; 0 disables the size trigger.")
_define("head_wal_compact_interval_s", 30.0,
        "Maximum age of a non-empty WAL segment before compaction "
        "runs regardless of size; 0 disables the time trigger.")
_define("head_done_replay_window_s", 15.0,
        "How far back (before the head connection was lost) an agent "
        "replays already-SENT completion-batch entries on rejoin: a "
        "batch can be TCP-delivered but never processed by a dying "
        "head, so the tail of sent entries is replayed and deduped "
        "head-side against the rehydrated mirror (exactly-once "
        "accounting). 0 replays only never-sent buffered entries.")
_define("agent_reconnect_window_s", 60.0,
        "How long a node agent keeps redialing a lost head before "
        "giving up and shutting down (reference raylets tolerate GCS "
        "downtime); 0 restores exit-on-disconnect.")
_define("store_put_block_s", 10.0,
        "Create-queueing backpressure (reference plasma "
        "create_request_queue.cc): when the object store is over "
        "capacity and nothing is spillable (all bytes pinned by "
        "in-flight tasks), a put parks up to this long for space to "
        "free before admitting the object over-cap with a warning. "
        "0 disables blocking.")
_define("memory_monitor_threshold", 0.95,
        "Node memory-usage fraction above which the per-node memory "
        "monitor kills a task worker to relieve pressure (reference "
        "raylet memory_monitor + worker_killing_policy.cc). 0 "
        "disables the monitor.")
_define("memory_monitor_refresh_s", 1.0,
        "Memory monitor poll period.")
_define("worker_pipeline_depth", 4,
        "Tasks dispatched to one worker before its previous task "
        "completes (the worker executes FIFO). Depth >1 overlaps the "
        "completion round-trip with execution — the reference's "
        "worker-lease pipelining. Under saturation, queued tasks ride "
        "the worker's existing resource grant (charged on predecessor "
        "completion), so depth also sets how many TASK/TASK_DONE "
        "frames coalesce per wire write. Blocked workers steal back "
        "their queued tail, so deadlock-safety is depth-independent. "
        "1 restores strict one-at-a-time dispatch.")
_define("wire_batch", True,
        "Micro-batch fire-and-forget control frames (TASK_DONE, decref "
        "floods, multi-spec dispatch) into coalesced writes — one "
        "BatchFrame envelope when the peer negotiated wire MINOR >= 1, "
        "else concatenated single frames in one syscall. 0 restores "
        "strict one-frame-per-send behavior.")
_define("wire_batch_max_frames", 64,
        "Coalescing queue flushes when this many frames are pending "
        "(also the per-frame cap of a DECREF_BATCH, clamped there to "
        "64 so its id list stays within the wire's structural-"
        "encoding bound).")
_define("wire_batch_delay_ms", 1.0,
        "Coalescing window (collect-then-flush): the first lazy frame "
        "opens a window of this width and every frame emitted inside "
        "it rides the same write, so any lazy frame waits at most "
        "~this long plus the flusher thread-wake latency. Reply-"
        "bearing and other eager sends bypass the queue entirely (and "
        "flush it first, preserving per-connection FIFO order).")
_define("wire_native", True,
        "Use the native frame engine (GIL-released socket read pump, "
        "scatter-gather flush, C envelope codec in "
        "native/core.c) for the wire hot path when the native library "
        "is available. 0 restores the pure-Python wire paths without "
        "touching the other native users (channel waits, CRC32C); "
        "RAY_TPU_DISABLE_NATIVE=1 disables all of them.")
_define("wire_native_codec", "auto",
        "Envelope codec selection when the native frame engine is on. "
        "'auto' (default): use the C codec only when the installed "
        "protobuf backend is the pure-Python one (~3x encode/decode "
        "there; the upb/C++ backends already serialize in C and beat "
        "per-frame ctypes calls). '1' forces the C codec, '0' forces "
        "the protobuf codec. Large pickled bodies always take the "
        "zero-copy scatter-gather emit path regardless.")
_define("wire_max_frame_bytes", 1 << 30,
        "Sanity bound on a frame's length prefix. A frame claiming to "
        "be larger is treated as a corrupt/hostile stream and the "
        "connection dies immediately — instead of the reader "
        "attempting a multi-GB allocation. Must comfortably exceed "
        "the largest legitimate frame (pull chunks are 4 MB; state "
        "replies can reach tens of MB).")
_define("shm_pool", True,
        "Reuse freed shm segments for subsequent large-object puts via "
        "a size-classed free pool (segments are renamed, not "
        "unlinked, while pooled) — skips the shm_open/ftruncate/page-"
        "zeroing cost on the large-object hot path. 0 restores "
        "unlink-on-free.")
_define("shm_pool_max_bytes", 256 * 1024 * 1024,
        "Total bytes the shm segment pool may hold; overflow falls "
        "back to the normal unlink-by-name path.")
_define("shm_pool_per_class", 4,
        "Segments kept per power-of-two size class in the shm pool.")
_define("node_rejoin_grace_s", 20.0,
        "After a head restart, how long rehydrated nodes have to "
        "re-register before they are declared dead and their actors/"
        "objects recovered.")
_define("pull_concurrency", 4,
        "Max concurrent object transfers a pull manager runs per "
        "process (reference pull_manager.cc active-pull bound); "
        "excess pulls queue. Requests for an object already in "
        "flight dedup onto the existing transfer regardless.")
_define("pull_max_inflight_bytes", 256 * 1024 * 1024,
        "Byte budget for in-flight pulled objects per pull manager "
        "(reference pull_manager.cc num_bytes_available_): a pull "
        "whose size would exceed it waits for running transfers to "
        "land. A single object larger than the budget is admitted "
        "alone. 0 = unbounded.")
_define("pull_pipeline_depth", 4,
        "Chunk requests a puller keeps in flight per transfer "
        "(reference object_buffer_pool chunked reads are windowed the "
        "same way): 1 restores strict request/reply lockstep, which "
        "makes every transfer latency-bound.")
_define("pull_chunk_retries", 2,
        "Per-pull retries after a dropped/expired chunk: the puller "
        "re-opens a session with the holder and resumes from the "
        "failed chunk index before giving up on that source.")
_define("pull_manifest", True,
        "Manifest (zero-copy) object transfer (r12, wire MINOR >= 5): "
        "pulls ask the holder for a manifest (payload + per-buffer "
        "sizes) and chunk bodies ride the Envelope raw field straight "
        "from the holder's mapped shm into the puller's pre-created "
        "segments — no materialize/pickle copies on either side. "
        "Negotiated per transfer: an old holder ignores the request "
        "flag and serves the blob protocol. 0 restores blob pulls "
        "everywhere.")
_define("pull_cut_through", True,
        "Cut-through relay (r12): a node mid-pull registers as a "
        "PARTIAL holder at its first landed chunk and serves already-"
        "landed chunk ranges to its broadcast children while its own "
        "pull is in flight, making tree depth cost per-chunk instead "
        "of per-object latency. Requires manifest transfers; 0 "
        "restores store-and-forward relay.")
_define("pull_partial_chunk_timeout_s", 20.0,
        "Per-chunk client-side deadline when pulling from a PARTIAL "
        "holder (its own pull may stall): on expiry the chunk counts "
        "as dropped and the normal retry / re-root-on-source "
        "machinery takes over, instead of burning the transfer's "
        "whole deadline on a stalled relay.")
_define("pull_session_ttl_s", 120.0,
        "Pull-session idle TTL on the serving side: sessions a dead "
        "puller abandoned are reaped on the next pull/chunk message "
        "(lazy sweep) and on the puller's connection close, "
        "releasing the materialized blob and the object pin.")
_define("bcast_fanout", 4,
        "Tree-broadcast fanout: each node that completes its copy "
        "serves at most this many children, so the source serves "
        "<= fanout transfers instead of N (reference object-manager "
        "push parity for the 1 GiB x 50-node envelope row).")
_define("bcast_timeout_s", 120.0,
        "Per-broadcast deadline: nodes still missing the object when "
        "it expires are reported as failed in the broadcast result.")
_define("trace", True,
        "Master switch for the distributed tracing plane (r9): span "
        "emission into the per-process flight recorder and trace-"
        "context propagation on the wire (Envelope trace_id/"
        "parent_span fields, MINOR >= 2 peers). 0 disables both — "
        "no spans are recorded and envelopes carry zero extra bytes "
        "(proto3 omits unset fields).")
_define("trace_ring", 4096,
        "Per-process flight-recorder capacity in span events (each a "
        "small tuple; 4096 ~ a few hundred KB). The ring wraps — "
        "newest events win, the watermark keeps counting so drops are "
        "visible. 0 disables recording (same effect as "
        "RAY_TPU_TRACE=0).")
_define("epoll", True,
        "Drive the read side of every head/agent connection from ONE "
        "shared event loop (r10): the native epoll API in core.c "
        "(epoll_wait with the GIL released, level-triggered, each "
        "ready fd drained through its C reassembly buffer) when the "
        "frame engine is on, a select()-based Python loop otherwise. "
        "0 restores a dedicated reader thread per connection. Worker "
        "processes always use per-connection readers (they hold one "
        "or two connections).")
_define("delegate", True,
        "Delegated bulk-lease scheduling (r10): the head grants "
        "agents batches of queued tasks in single NODE_LEASE_BATCH "
        "frames instead of per-spec sends, suppresses per-task "
        "dispatch events, and agents report completions in coalesced "
        "TASK_DONE_BATCH frames. Negotiated per connection (peer "
        "wire MINOR >= 3); 0 restores per-task round-trips. The head "
        "keeps ownership: lease revoke, steal, and lineage resubmit "
        "all still work.")
_define("delegate_lease_batch", 64,
        "Max specs per NODE_LEASE_BATCH: the head-side lease buffer "
        "flushes when this many specs are parked for one agent (or "
        "when the delegate_lease_delay_ms window closes).")
_define("delegate_lease_delay_ms", 1.0,
        "Collect-then-flush window for the head-side lease buffer: "
        "the first parked spec opens a window of this width; every "
        "spec routed to the same agent inside it rides one "
        "NODE_LEASE_BATCH frame.")
_define("delegate_done_batch", 64,
        "Max completions per TASK_DONE_BATCH: the agent-side "
        "completion buffer flushes at this count (or when the "
        "delegate_done_delay_ms window closes, or before any other "
        "state-bearing send — ordering with worker_lost/refcount "
        "traffic is preserved).")
_define("delegate_done_delay_ms", 2.0,
        "Collect-then-flush window for the agent-side completion "
        "buffer.")
_define("delegate_max_inflight", 0,
        "Resource-budget cap on tasks leased to one agent but not "
        "yet reported done; specs beyond it stay parked in the "
        "head-side lease buffer until completions free budget. "
        "0 = unbounded (the agent's own scheduler remains the "
        "authoritative resource ledger either way).")
_define("metrics", True,
        "Master switch for the cluster metrics plane (r11): runtime-"
        "instrumented series (task latency histograms by phase, lease/"
        "poller/object-plane/shm-pool telemetry) registered into the "
        "per-process util.metrics registry, plus the METRICS_DUMP "
        "cluster scrape. 0 disables instrumentation entirely — hot "
        "paths skip every observe behind one memoized gate and no "
        "runtime series are ever registered (zero metric bytes, the "
        "RAY_TPU_TRACE=0 discipline).")
_define("metrics_ttl_s", 15.0,
        "Stale-series expiry in the head-side cluster collector: a "
        "process (worker/agent) that stops answering METRICS_DUMP "
        "keeps its last-seen series in /metrics for this long, then "
        "they disappear — removed nodes/workers cannot linger "
        "forever, while one missed scrape doesn't flap the view.")
_define("metrics_ring", 120,
        "Head-side metrics retention ring: how many collection "
        "samples (one summary per cluster scrape) the head keeps for "
        "the dashboard sparklines and the autoscaler's windowed "
        "queue-latency signal. 0 disables retention.")
_define("metrics_min_scrape_s", 1.0,
        "Rate limit on cluster metrics fan-outs: collections "
        "requested closer together than this (dashboard auto-refresh "
        "+ autoscaler both pulling) reuse the cached merge instead of "
        "re-fanning METRICS_DUMP to every process.")
_define("autoscale_queue_latency_s", 0.0,
        "Autoscaler queue-latency signal (r11): when > 0, the "
        "autoscaler scales UP one node whenever the cluster task "
        "queue-wait p95 over the recent window exceeds this many "
        "seconds — even if resource-shape demand alone would not "
        "trigger a launch (the groundwork for latency-SLO serving "
        "autoscaling). 0 disables the signal.")
_define("autoscale_queue_latency_window_s", 30.0,
        "Window over the metrics retention ring used to compute the "
        "queue-wait p95 for the autoscaler signal (recent "
        "distribution, not the process-lifetime cumulative one).")
_define("autoscale_queue_latency_cooldown_s", 30.0,
        "Minimum seconds between latency-driven scale-ups: the p95 "
        "stays high until new capacity drains the queue, so without a "
        "cooldown the signal would launch a node per update tick.")
_define("channel_ring_depth", 2,
        "Compiled-DAG channel ring slots (r13): how many published-"
        "but-unconsumed messages a channel buffers before the writer "
        "blocks. 1 restores the single-slot r5 behavior (the writer "
        "waits for every reader before each publish — no transfer/"
        "compute overlap); 2 double-buffers, which is what lets an "
        "MPMD pipeline stage compute microbatch m+1 while m is still "
        "in flight to its neighbor. Applies to both the shm and wire "
        "channel transports.")
_define("channel_wire_attach_timeout_s", 30.0,
        "How long a wire-channel reader waits for its attach "
        "handshake with the writer-side channel server before the "
        "endpoint raises (the writer's exec loop may still be "
        "starting).")
_define("elastic", True,
        "Master switch for elastic training (r14): with a "
        "ScalingConfig(elastic=ElasticConfig(...)) the JaxTrainer "
        "reshapes its worker group on node loss/gain (dp mesh shrinks "
        "or grows), auto-restores from the latest checkpoint with "
        "broadcast-tree weight delivery, and keeps step accounting "
        "exact. 0 forces the classic whole-group restart path even "
        "when an ElasticConfig is present.")
_define("elastic_poll_s", 0.25,
        "Driver-side poll period in the elastic training loop: how "
        "often the trainer checks node events (DRAINING/ALIVE/DEAD) "
        "and capacity while waiting on worker results. Smaller reacts "
        "faster to preemption notices at slightly more head traffic.")
_define("elastic_capacity_timeout_s", 60.0,
        "How long an elastic fit() waits for cluster capacity to "
        "reach ElasticConfig.min_workers (initially and after a node "
        "loss) before giving up and surfacing the failure.")
_define("elastic_max_reshapes", 16,
        "Bound on elastic reshapes (node-loss restores + grows) in "
        "one fit(): a cluster flapping faster than training progresses "
        "surfaces as an error instead of looping forever.")
_define("drain_deadline_s", 30.0,
        "Default drain window for a preemption notice "
        "(Autoscaler.on_preemption_notice with deadline_s=None): the "
        "node is released when the drain is acknowledged (elastic "
        "trainer checkpoint flushed) or this many seconds elapse, "
        "whichever comes first.")
_define("head_shards", 8,
        "Stripe count for the head's hot tables (r16): the ref/pin "
        "table, live-task spec mirror, lineage mirror, and object "
        "directory are split into this many independently locked "
        "shards keyed by task/object id, so submits, completions, and "
        "decref storms stop convoying through one controller lock at "
        "100k-task scale. Rounded up to a power of two. 0 (or 1) "
        "reverts to the single-shard pre-r16 topology.")
_define("head_lineage_max", 100_000,
        "Resident-entry cap on the head's lineage mirror (return "
        "object id -> producing spec, kept for lost-copy "
        "reconstruction). FIFO eviction past the cap bounds head "
        "memory under sustained 100k-task in-flight populations; an "
        "evicted entry only disables lineage reconstruction for that "
        "object (reference max_lineage_bytes degrades the same way). "
        "0 = unbounded.")
_define("decref_delta", True,
        "Route worker decref storms through the node agent as "
        "coalesced per-object count deltas (r16 NODE_DECREF_DELTA): "
        "the agent merges its workers' DECREF/DECREF_BATCH traffic "
        "into one seq-numbered {object_id: n} frame per flush window "
        "and the head applies each frame per-shard (one stripe-lock "
        "round trip per shard, not per release), with rejoin replays "
        "deduped by a per-node watermark. Requires the head to speak "
        "wire MINOR >= 7; 0 restores per-connection DECREF_BATCH "
        "forwarding.")
_define("decref_delta_delay_ms", 2.0,
        "Collect-then-flush window for the agent-side decref-delta "
        "buffer (the delegate_done_delay_ms discipline): the first "
        "parked release opens a window of this width; every release "
        "arriving inside it rides the same NODE_DECREF_DELTA frame.")
_define("decref_delta_max", 512,
        "Distinct object ids parked in the agent's decref-delta "
        "buffer that force an immediate flush (bounds both frame size "
        "and how much release traffic an agent crash can lose).")
_define("trace_sample", 64,
        "Trace sampling stride (r16): the head starts a trace for 1 "
        "in this many root task submissions and propagates the "
        "decision in the existing spec/envelope trace fields, so a "
        "sampled task is whole-or-nothing across every process it "
        "touches while unsampled tasks pay zero ring writes and zero "
        "wire bytes (exactly like RAY_TPU_TRACE=0). Nested submissions "
        "inside a sampled trace inherit it. 1 traces every task; 0 "
        "reverts to the pre-r16 always-trace behavior.")
_define("direct_actor", True,
        "Direct actor call plane (r18): callers resolve an actor's "
        "endpoint once (ACTOR_RESOLVE), dial the hosting node's "
        "listener, and stream calls over that one connection with "
        "replies returning inline — the head drops out of the steady-"
        "state path (it stays the lifecycle owner via the caller's "
        "coalesced ACTOR_INFLIGHT_DELTA mirror). Requires the peers "
        "to speak wire MINOR >= 8; stale endpoints NACK with a "
        "redirect-to-head fallback. 0 restores the fully head-routed "
        "actor path (byte-identical to r17).")
_define("direct_actor_worker", True,
        "Serve direct actor calls from the hosting WORKER's own "
        "socket (each worker opens a tiny listener and reports its "
        "port at REGISTER): caller -> worker -> caller, two legs "
        "total. 0 restores agent-hosted direct serving (caller -> "
        "agent -> worker -> agent -> caller), which also remains the "
        "automatic fallback while a worker's port is not yet known "
        "head-side (heartbeat lag) or its listener failed to bind.")
_define("direct_actor_stall_s", 10.0,
        "How long a get() on a direct-call reply future waits before "
        "falling back to the normal head-routed GET path. Covers the "
        "silent-partition case: the hosting node vanished without a "
        "FIN, the head declares it dead and errors the mirrored "
        "in-flight calls, and the fallback get resolves that error "
        "instead of hanging on the dead connection. Must comfortably "
        "exceed heartbeat_timeout_s.")
_define("direct_actor_delta_delay_ms", 25.0,
        "Collect-then-flush window for a remote caller's "
        "ACTOR_INFLIGHT_DELTA buffer (the decref-delta discipline): "
        "the first parked add/done opens a window of this width; "
        "everything arriving inside it rides one frame to the head. "
        "Wide by design — nothing in the delta is latency-critical "
        "(the caller holds a call-lifetime borrow on arg refs, so "
        "the head-side pin is belt-and-braces, and dones only "
        "release pins), and a sync caller at ~1k calls/s amortizes "
        "to well under 0.1 head frames per call.")
_define("direct_actor_delta_max", 64,
        "Buffered ACTOR_INFLIGHT_DELTA entries that force an "
        "immediate flush (bounds frame size and how much mirror "
        "state a caller crash can lose).")
_define("direct_actor_delta_delay_max_ms", 250.0,
        "Ceiling for the ADAPTIVE delta window (r20): a caller whose "
        "delta frames flush near-empty (a sparse caller, e.g. an RL "
        "env-runner pacing tens of act()/s against env stepping) "
        "doubles its collect window per flush up to this cap, so "
        "mirror frames amortize by call count instead of by wall "
        "clock; a near-full frame snaps the window back to "
        "direct_actor_delta_delay_ms. Bounds both mirror lag and "
        "crash-loss scope for slow callers.")
_define("llm_stream", True,
        "LLM serving token transport (serve/llm): 1 streams tokens "
        "over a peer-dialed push connection to the engine replica "
        "(r18-style direct plane — the head never sees a token "
        "frame); 0 falls back to the polled next_tokens actor-call "
        "path through the ordinary request plane.")
_define("llm_page_size", 16,
        "KV-cache page size in token positions. Every sequence's "
        "cache occupancy is a whole number of pages; smaller pages "
        "waste less on short tails but grow the page tables.")
_define("llm_max_batch", 8,
        "Continuous-batching decode width per engine replica: the "
        "step loop decodes up to this many in-flight sequences per "
        "iteration (the decode kernel is compiled once at this "
        "padded width).")
_define("llm_step_delay_s", 0.0,
        "Debug/chaos pacing: sleep this long between engine "
        "iterations. Stretches generations so fault-injection tests "
        "can land a kill or partition mid-stream; keep 0 in "
        "production.")
_define("llm_stream_wait_s", 0.5,
        "Polled token fallback (llm_stream=0): how long next_tokens "
        "parks server-side waiting for fresh tokens before returning "
        "an empty slice — converts client busy-polling into bounded "
        "server-side waits.")
_define("rl_ring_depth", 2,
        "Sebulba RL trajectory rings (rllib/sebulba): wire-channel "
        "ring depth between each env-runner and the learner. The "
        "depth is simultaneously the queue bound and the policy-"
        "staleness bound — a runner blocks writing shard seq when "
        "the learner has not acked seq - depth, so no consumed shard "
        "can be more than depth+2 policy versions behind (producing "
        "+ in-ring + consuming) per runner at publish interval 1.")
_define("rl_infer_max_batch", 64,
        "Sebulba inference actors: admission cap — at most this many "
        "parked act() requests are coalesced into one stacked "
        "forward pass per admission iteration.")
_define("rl_infer_wait_ms", 2.0,
        "Sebulba inference actors: admission window — after the "
        "first act() request arrives, the step loop waits this long "
        "for more callers to park before launching the batched "
        "forward. 0 disables coalescing (one forward per wakeup).")
_define("rl_step_delay_s", 0.0,
        "Debug/chaos pacing: sleep this long per Sebulba inference "
        "forward pass. Stretches rollouts so fault-injection tests "
        "can land a kill or partition mid-stream; keep 0 in "
        "production.")
_define("rl_publish_interval", 1,
        "Sebulba learner: publish refreshed weights to inference "
        "actors every N updates (ray_tpu.put once + broadcast-tree "
        "fanout + versioned set_weights). Larger values trade "
        "staleness for publish bandwidth.")
_define("scheduler_locality", True,
        "Locality-aware node selection: prefer placing a task on a "
        "feasible node already holding the most argument bytes "
        "(object-directory lookup; reference locality_task_spreading "
        "hybrid-policy input). 0 restores pure pack/spread.")


class _Config:
    """Attribute access resolves registry entries with env override."""

    def __init__(self):
        self._cache: Dict[str, Any] = {}
        # Bumped by reload(): per-call-site memos of derived config
        # state (e.g. native.frame_engine_enabled on the per-frame hot
        # path) key on this instead of re-reading the environment.
        # Contract: flipping a RAY_TPU_* env var takes effect after
        # CONFIG.reload() — which the tests and bench already call.
        self._gen: int = 0

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        cache = self.__dict__["_cache"]
        if name in cache:
            return cache[name]
        entry = _REGISTRY.get(name)
        if entry is None:
            raise AttributeError(
                f"unknown config {name!r}; known: {sorted(_REGISTRY)}")
        env = os.environ.get("RAY_TPU_" + name.upper())
        value = entry.default if env is None else entry.parse(env)
        cache[name] = value
        return value

    def reload(self) -> None:
        """Drop cached values so env overrides re-apply (tests)."""
        self.__dict__["_cache"].clear()
        self.__dict__["_gen"] += 1

    def describe(self) -> Dict[str, Dict[str, Any]]:
        """All knobs with current value, default, env var name, doc."""
        return {
            name: {
                "value": getattr(self, name),
                "default": e.default,
                "env": "RAY_TPU_" + name.upper(),
                "doc": e.doc,
            } for name, e in sorted(_REGISTRY.items())}


CONFIG = _Config()
