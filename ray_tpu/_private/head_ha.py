"""Head HA (r15): write-ahead-logged, restartable control plane.

The head's tables were snapshotted at 1 Hz (r5) — a crash lost up to a
second of submits/completions and the snapshot never covered the spec
mirror, the delegated lease ledgers, or live-task accounting. This
module closes the gap the way the reference closes it for the GCS
(PAPER.md L0: GCS state persists to Redis precisely because the head
is otherwise the cluster's SPOF):

- ``WriteAheadLog``: an append-only log of state-mutating head events.
  Records are CRC32-framed (``[len u32][crc u32][payload]``); a torn
  tail — the crash landed mid-write — truncates at the last good
  frame instead of poisoning recovery. Appends are buffered and a
  single flusher thread group-commits them with ONE ``write`` + ONE
  ``fsync`` per ``RAY_TPU_HEAD_WAL_FSYNC_MS`` window, so per-event
  durability costs a list append, not a syscall.
- Snapshot+truncate compaction: when the active segment passes
  ``RAY_TPU_HEAD_WAL_COMPACT_BYTES`` (or the compact interval), the
  segment rotates, a fresh snapshot is taken, and the old segment is
  deleted. The snapshot embeds the WAL sequence frontier it covers
  (captured under the controller lock, so mutate+log pairs are atomic
  w.r.t. the capture); replay skips records at or below the frontier,
  which makes replay idempotent even across the rotation window.
- ``HeadPersistence``: the recovery coordinator. It loads the newest
  intact snapshot (version+checksum framed; a corrupt blob falls back
  to the previous good one), replays the WAL tail into the controller
  tables, and parks each agent's rehydrated spec mirror + lease
  ledger until that agent rejoins — at which point the mirror is
  reconciled against the agent's reported in-flight set: tasks the
  agent never received are re-placed exactly once, tasks it is still
  draining stay mirrored, and completion batches it replays are
  deduped by the ordinary mirror pop.

Record design note: records are SET-semantics wherever an increment
would make replay order- or multiplicity-sensitive — refcounts and
pins are logged as absolute values (coalesced into one ``refs`` record
per flush window, the WAL's decref-batch analogue), mirrors and
directories as keyed add/remove, and node ``incarnation`` records
(r17 fencing epochs) as absolute values merged by max, so replaying a
rotated segment can never roll an epoch back and resurrect a zombie.
Replaying a tail twice therefore converges to the same tables, which
is what the recovery matrix in ``tests/test_head_ha.py`` (and the
incarnation round-trip in ``tests/test_membership.py``) asserts.
"""
from __future__ import annotations

import logging
import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterable, Optional

log = logging.getLogger(__name__)

_FRAME = struct.Struct("<II")            # payload length, crc32(payload)
SNAP_MAGIC = b"RTPUSNP1"
_SNAP_HDR = struct.Struct("<II")         # version, crc32(blob)
SNAP_VERSION = 1

# terminal task-event states: these pop the live-task table
TERMINAL_TASK_STATES = ("FINISHED", "FAILED", "CANCELLED")


def _encode(obj: Any) -> bytes:
    """Records hold raw user task args (closures) exactly like the
    snapshot does — plain pickle where it works, cloudpickle where it
    must (same rationale as ``Controller.snapshot_state``)."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        import cloudpickle
        return cloudpickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def frame_snapshot(blob: bytes) -> bytes:
    """Version+checksum envelope for a snapshot blob: a partially
    written or bit-rotted file is DETECTED at restore instead of
    pickling garbage into half-initialized tables."""
    return SNAP_MAGIC + _SNAP_HDR.pack(SNAP_VERSION,
                                       zlib.crc32(blob) & 0xFFFFFFFF) + blob


def unframe_snapshot(data: bytes) -> bytes:
    """Inverse of ``frame_snapshot``; raises ValueError on a corrupt or
    torn blob. Pre-r15 snapshots (no magic) pass through unchanged so
    an upgraded head still restores its last pre-upgrade state."""
    if not data.startswith(SNAP_MAGIC):
        return data                       # legacy unframed blob
    hdr = data[len(SNAP_MAGIC):len(SNAP_MAGIC) + _SNAP_HDR.size]
    if len(hdr) < _SNAP_HDR.size:
        raise ValueError("snapshot header torn")
    version, crc = _SNAP_HDR.unpack(hdr)
    if version > SNAP_VERSION:
        raise ValueError(f"snapshot version {version} from the future")
    blob = data[len(SNAP_MAGIC) + _SNAP_HDR.size:]
    if zlib.crc32(blob) & 0xFFFFFFFF != crc:
        raise ValueError("snapshot checksum mismatch (torn write?)")
    return blob


def write_snapshot_file(path: str, blob: bytes) -> None:
    """Atomic, torn-write-proof snapshot publication (shared by the
    WAL and snapshot-only modes): frame (version+crc) → tmp file →
    flush+fsync → rotate the current snapshot to ``.prev`` → rename
    into place. A crash anywhere in the sequence leaves at least one
    intact, verifiable blob."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(frame_snapshot(blob))
        f.flush()
        os.fsync(f.fileno())
    if os.path.exists(path):
        os.replace(path, path + ".prev")
    os.replace(tmp, path)


def load_snapshot_file(path: str):
    """Newest intact snapshot blob as ``(blob, used_fallback)``: the
    current file, else the previous good one — a torn current blob
    must not zero the head's tables (the pre-r15 failure mode).
    ``(None, False)`` when neither verifies."""
    for candidate, fallback in ((path, False), (path + ".prev", True)):
        if not os.path.exists(candidate):
            continue
        try:
            with open(candidate, "rb") as f:
                return unframe_snapshot(f.read()), fallback
        except Exception:
            log.exception("head snapshot %s unusable", candidate)
    return None, False


class WriteAheadLog:
    """Group-committed, CRC-framed append log with rotate/compact.

    ``append`` assigns a monotonic sequence number under the buffer
    lock and parks the already-encoded frame; the flusher thread
    drains the buffer with one write+fsync per window. ``log_ref``
    coalesces absolute refcount/pin values into ONE ``refs`` record
    per flush (a decref storm costs a dict update per object, not a
    record per event)."""

    def __init__(self, path: str, fsync_ms: float = 5.0):
        self.path = path
        self._fsync_s = max(0.0, fsync_ms) / 1000.0
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        self._lock = threading.Lock()
        # serializes fd use (write/fsync/rotate/close) WITHOUT holding
        # the buffer lock across syscalls: appends never block on an
        # in-flight fsync, and compaction can never close the fd under
        # a concurrent flush (ordering: _lock before _io, never inverse)
        self._io = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._buf: list[bytes] = []
        self._pending_refs: Dict[str, tuple] = {}
        self._seq = 0
        self._flushed_seq = 0          # highest seq durably on disk
        self._flush_cv = threading.Condition(self._lock)
        self._closed = False
        # stats
        self.records = 0
        self.bytes_written = int(os.path.getsize(path)
                                 if os.path.exists(path) else 0)
        self.fsyncs = 0
        self.compactions = 0
        self._fsync_ns: list[int] = []     # ring of recent durations
        self._segment_bytes = self.bytes_written
        self._segment_opened = time.monotonic()
        self._flusher = threading.Thread(
            target=self._flush_loop, name="rtpu-head-wal", daemon=True)
        self._flusher.start()

    # ------------------------------------------------------- appending
    def current_seq(self) -> int:
        """Sequence frontier (captured by snapshots). Taking the WAL
        lock here is safe from inside controller-locked sections: the
        WAL never calls back out."""
        with self._lock:
            return self._seq

    def advance_seq(self, floor: int) -> None:
        """Seed the sequence counter past recovered state (r15 review
        fix): a restarted head appends to the SAME segment the old
        process wrote, and a counter restarting at 0 would (a) mint
        seqs the snapshot frontier wrongly skips and (b) collide with
        the old records still in the file until first compaction —
        breaking exact-frontier replay on a double crash."""
        with self._lock:
            if floor > self._seq:
                self._seq = floor
                self._flushed_seq = max(self._flushed_seq, floor)

    def append(self, rtype: str, data: Any) -> int:
        """Park one record for the next group commit. The payload is
        encoded NOW, not at flush — specs mutate after submit
        (retries_used, trace parents) and the record must capture the
        state that was logged, not whatever the object looks like when
        the flusher gets to it."""
        with self._lock:
            if self._closed:
                return self._seq
            self._seq += 1
            payload = _encode((self._seq, rtype, data))
            self._buf.append(_FRAME.pack(len(payload),
                                         zlib.crc32(payload) & 0xFFFFFFFF)
                             + payload)
            self._cv.notify()
            return self._seq

    def log_ref(self, object_id: str, refcount: int, pins: int) -> None:
        """Absolute refcount+pin state for one object; coalesced —
        last value per object wins within a flush window, and replay
        SETS rather than increments, so duplicated replay is a no-op."""
        with self._lock:
            if self._closed:
                return
            self._pending_refs[object_id] = (int(refcount), int(pins))
            self._cv.notify()

    # -------------------------------------------------------- flushing
    def _drain_locked(self) -> list[bytes]:
        frames, self._buf = self._buf, []
        if self._pending_refs:
            refs, self._pending_refs = self._pending_refs, {}
            self._seq += 1
            payload = _encode((self._seq, "refs", refs))
            frames.append(_FRAME.pack(len(payload),
                                      zlib.crc32(payload) & 0xFFFFFFFF)
                          + payload)
        return frames

    def _flush_once(self) -> None:
        with self._lock:
            frames = self._drain_locked()
            drained_seq = self._seq
        if not frames:
            # nothing to write: durability of already-drained frames is
            # advanced by whichever write drained them (flusher or
            # compaction), never here — an empty pass must not declare
            # a concurrent in-flight write durable
            return
        blob = b"".join(frames)
        t0 = time.perf_counter_ns()
        with self._io:
            # the fd is re-read under the io lock: a concurrent
            # compaction may have rotated it, and these frames landing
            # in the NEW segment is fine (replay sorts by seq; their
            # mutations predate the compaction snapshot's frontier)
            os.write(self._fd, blob)
            os.fsync(self._fd)
        dt = time.perf_counter_ns() - t0
        with self._lock:
            self.records += len(frames)
            self.bytes_written += len(blob)
            self._segment_bytes += len(blob)
            self.fsyncs += 1
            self._fsync_ns.append(dt)
            if len(self._fsync_ns) > 256:
                del self._fsync_ns[:128]
            self._flushed_seq = max(self._flushed_seq, drained_seq)
            self._flush_cv.notify_all()

    def _flush_loop(self) -> None:
        while True:
            with self._cv:
                while (not self._buf and not self._pending_refs
                       and not self._closed):
                    self._cv.wait(timeout=1.0)
                if self._closed and not self._buf \
                        and not self._pending_refs:
                    return
            # collect-then-flush: let the window fill so one fsync
            # covers every record emitted inside it
            if self._fsync_s > 0:
                time.sleep(self._fsync_s)
            try:
                self._flush_once()
            except Exception:
                log.exception("head WAL flush failed")
                time.sleep(0.1)

    def sync(self, timeout: float = 5.0) -> None:
        """Block until everything appended BEFORE this call is on disk
        — tracked by sequence number, so an in-flight flush of older
        frames completing cannot satisfy the wait early (r15 review
        fix of the event-based version)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            target = self._seq
            if self._pending_refs:
                target += 1            # the refs record mints one more
            while self._flushed_seq < target and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._flush_cv.wait(remaining)

    # ------------------------------------------------------ compaction
    def should_compact(self, compact_bytes: int,
                       compact_interval_s: float) -> bool:
        with self._lock:
            if self._segment_bytes <= 0:
                return False
            if compact_bytes > 0 and self._segment_bytes >= compact_bytes:
                return True
            return (compact_interval_s > 0
                    and time.monotonic() - self._segment_opened
                    >= compact_interval_s
                    and self._segment_bytes > 0)

    def compact(self, snapshot_fn: Callable[[], None]) -> bool:
        """Rotate the active segment, take a fresh snapshot, delete the
        rotated segment. Crash-safe at every step: recovery replays
        ``path.old`` (if present) then ``path`` in sequence order, and
        the snapshot's embedded frontier skips anything it already
        covers — so a crash between rotation and snapshot publication
        loses nothing and duplicates nothing."""
        old = self.path + ".old"
        if os.path.exists(old):
            # a PREVIOUS compaction's snapshot failed and its rotated
            # segment is still the only copy of those records —
            # rotating again would destroy it (r15 review fix).
            # Snapshot first (the frontier covers the retained segment
            # too), clear it on success, and rotate on the next pass.
            try:
                snapshot_fn()
            except Exception:
                log.exception("head WAL compaction snapshot failed; "
                              "keeping retained segment")
                return False
            try:
                os.unlink(old)
            except OSError:
                pass
            with self._lock:
                self.compactions += 1
            return True
        with self._lock:
            if self._closed:
                return False
            # flush the buffer into the outgoing segment first so its
            # records are on disk before the snapshot frontier is read
            frames = self._drain_locked()
            drained_seq = self._seq
        with self._io:
            if frames:
                blob = b"".join(frames)
                os.write(self._fd, blob)
                os.fsync(self._fd)
            os.replace(self.path, old)
            os.close(self._fd)
            self._fd = os.open(self.path,
                               os.O_WRONLY | os.O_CREAT | os.O_APPEND)
        with self._lock:
            if frames:
                self.records += len(frames)
                self.bytes_written += sum(len(f) for f in frames)
                self.fsyncs += 1
            self._flushed_seq = max(self._flushed_seq, drained_seq)
            self._flush_cv.notify_all()
            self._segment_bytes = 0
            self._segment_opened = time.monotonic()
        try:
            snapshot_fn()                  # captures the seq frontier
        except Exception:
            # snapshot failed: keep BOTH segments — recovery still has
            # the previous snapshot plus the full record trail
            log.exception("head WAL compaction snapshot failed; "
                          "keeping rotated segment")
            return False
        try:
            os.unlink(old)
        except OSError:
            pass
        with self._lock:
            self.compactions += 1
        return True

    # -------------------------------------------------------- lifecycle
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cv.notify_all()
            self._flush_cv.notify_all()    # unblock sync() waiters
        self._flusher.join(timeout=5.0)
        try:
            self._flush_once()             # final drain (flusher exited)
        except Exception:
            pass
        try:
            with self._io:
                os.close(self._fd)
        except OSError:
            pass

    def stats(self) -> dict:
        with self._lock:
            ns = sorted(self._fsync_ns)
            p = (lambda q: round(
                ns[min(len(ns) - 1, int(q * len(ns)))] / 1e6, 3)
                if ns else None)
            return {
                "path": self.path,
                "seq": self._seq,
                "records": self.records,
                "bytes": self.bytes_written,
                "segment_bytes": self._segment_bytes,
                "fsyncs": self.fsyncs,
                "fsync_p50_ms": p(0.50),
                "fsync_p99_ms": p(0.99),
                "compactions": self.compactions,
                "buffered": len(self._buf) + len(self._pending_refs),
            }


def read_wal(path: str) -> list[tuple]:
    """Decode one segment: ``[(seq, rtype, data), ...]``. A torn tail
    (crash mid-write) truncates at the last frame whose length and
    CRC both verify — everything before it is intact by construction
    (frames are appended in one write, in order)."""
    out: list[tuple] = []
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return out
    off = 0
    n = len(data)
    while off + _FRAME.size <= n:
        ln, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + ln
        if ln <= 0 or end > n:
            break                          # torn tail
        payload = data[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            break                          # torn/corrupt tail
        try:
            rec = pickle.loads(payload)
        except Exception:
            break
        out.append(rec)
        off = end
    return out


class _PendingNode:
    """A rehydrated agent's mirror + lease ledger, parked until the
    agent rejoins (or its rejoin grace expires)."""

    def __init__(self, work: dict, leased: set):
        self.work = dict(work)             # key -> (spec, dispatched)
        self.leased = set(leased)


class HeadPersistence:
    """Recovery coordinator + live logging front-end for the runtime.

    Lifecycle: construct → ``recover()`` (replays snapshot+WAL into
    the controller and parks per-node mirrors) → ``activate()`` (live
    records start flowing). Logging before activation is suppressed so
    replay can drive the ordinary controller methods without
    re-logging its own input."""

    def __init__(self, snapshot_path: str, wal_path: str,
                 fsync_ms: float = 5.0, compact_bytes: int = 8 << 20,
                 compact_interval_s: float = 30.0):
        self.snapshot_path = snapshot_path
        self.wal = WriteAheadLog(wal_path, fsync_ms=fsync_ms)
        self._compact_bytes = int(compact_bytes)
        self._compact_interval_s = float(compact_interval_s)
        self._active = False
        self._lock = threading.Lock()
        self.pending_nodes: Dict[str, _PendingNode] = {}
        # recovery/replay observability
        self.recovered = {"snapshot": False, "snapshot_fallback": False,
                          "wal_records": 0, "wal_skipped": 0,
                          "live_tasks": 0, "mirrored_tasks": 0,
                          "resubmitted": 0, "replayed_completions": 0,
                          "deduped_completions": 0}
        self.restored_task_ids: set[str] = set()
        self.last_snapshot_at: Optional[float] = None

    # ------------------------------------------------------- live path
    def active(self) -> bool:
        return self._active

    def activate(self) -> None:
        self._active = True

    def log(self, rtype: str, data: Any) -> None:
        if self._active:
            self.wal.append(rtype, data)

    def log_ref(self, object_id: str, refcount: int, pins: int) -> None:
        if self._active:
            self.wal.log_ref(object_id, refcount, pins)

    def wal_seq(self) -> int:
        return self.wal.current_seq()

    # ------------------------------------------------------- snapshots
    def write_snapshot(self, blob: bytes) -> None:
        write_snapshot_file(self.snapshot_path, blob)
        self.last_snapshot_at = time.monotonic()

    def load_snapshot(self) -> Optional[bytes]:
        blob, fallback = load_snapshot_file(self.snapshot_path)
        if blob is not None:
            self.recovered["snapshot"] = True
            self.recovered["snapshot_fallback"] = fallback
            if fallback:
                log.warning("head snapshot %s corrupt; restored from "
                            "the previous good generation",
                            self.snapshot_path)
        return blob

    # -------------------------------------------------------- recovery
    def wal_tail(self) -> list[tuple]:
        """Every retained record in sequence order: a rotated-but-not-
        deleted segment (compaction crashed mid-way) first, then the
        active segment."""
        recs = read_wal(self.wal.path + ".old") + read_wal(self.wal.path)
        recs.sort(key=lambda r: r[0])
        return recs

    def replay(self, controller, records: Iterable[tuple],
               frontier: int, mirrors: Dict[str, dict],
               leases: Dict[str, set]) -> int:
        """Apply WAL records newer than the snapshot frontier to the
        controller tables and the parked per-node mirrors. Record
        application is set-semantics throughout, so replaying a tail
        (or parts of it) more than once converges — the
        torn-compaction path depends on this."""
        applied = 0
        for seq, rtype, data in records:
            if seq <= frontier:
                self.recovered["wal_skipped"] += 1
                continue
            try:
                if rtype in ("madd", "lease"):
                    if rtype == "madd":
                        node_id, key = data
                        mirrors.setdefault(node_id, {})[key] = None
                    else:
                        node_id, ids = data
                        leases.setdefault(node_id, set()).update(ids)
                else:
                    controller.apply_wal_record(rtype, data)
                applied += 1
            except Exception:
                log.exception("head WAL replay failed on %r", rtype)
        self.recovered["wal_records"] = applied
        return applied

    def park_node(self, node_id: str, work: dict, leased: set) -> None:
        with self._lock:
            self.pending_nodes[node_id] = _PendingNode(work, leased)
            self.recovered["mirrored_tasks"] += len(work)

    def take_pending_node(self, node_id: str) -> Optional[_PendingNode]:
        with self._lock:
            return self.pending_nodes.pop(node_id, None)

    def pending_mirrors(self) -> Dict[str, dict]:
        """Mirror view of nodes still awaiting rejoin — merged into
        snapshots taken during the grace window so a compaction there
        cannot drop a not-yet-reclaimed node's work."""
        with self._lock:
            return {nid: {"work": dict(p.work), "leased": list(p.leased)}
                    for nid, p in self.pending_nodes.items()}

    def note_replayed_completion(self, task_id: str,
                                 deduped: bool) -> None:
        if deduped:
            self.recovered["deduped_completions"] += 1
        else:
            self.recovered["replayed_completions"] += 1
        self.restored_task_ids.discard(task_id)

    # ---------------------------------------------------------- stats
    def maybe_compact(self, snapshot_fn: Callable[[], None]) -> bool:
        if not self._active:
            return False
        if not self.wal.should_compact(self._compact_bytes,
                                       self._compact_interval_s):
            return False
        return self.wal.compact(snapshot_fn)

    def stats(self) -> dict:
        with self._lock:
            pending = {nid: len(p.work)
                       for nid, p in self.pending_nodes.items()}
        age = (None if self.last_snapshot_at is None
               else round(time.monotonic() - self.last_snapshot_at, 3))
        return {
            "enabled": True,
            "active": self._active,
            "wal": self.wal.stats(),
            "last_snapshot_age_s": age,
            "pending_rejoin_mirrors": pending,
            "recovered": dict(self.recovered),
        }

    def close(self) -> None:
        self._active = False
        self.wal.close()
