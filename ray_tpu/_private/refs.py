"""ObjectRef: the distributed future handle.

Parity: reference python/ray/_raylet.pyx ObjectRef + C++ reference counting
(src/ray/core_worker/reference_count.cc). v0 protocol is centralized: the
driver's controller owns all refcounts. Driver-held refs inc/dec; refs
deserialized inside workers are *borrows* that do not decrement (the
spec-pin held by the submitting side outlives the borrow), a simplification
of the reference's borrower protocol (reference reference_count.h:115-117)
that is safe because borrows cannot outlive the task that carries them
unless returned — and returned refs re-enter driver tracking.
"""
from __future__ import annotations

from typing import Optional

from ray_tpu._private import context as _context


class ObjectRef:
    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: str, owned: bool = True):
        self._id = object_id
        self._owned = owned

    @property
    def object_id(self) -> str:
        return self._id

    def hex(self) -> str:
        return self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __reduce__(self):
        # Cross-process transfer: reconstruct as a borrowed (non-counting) ref.
        return (_reconstruct_borrowed, (self._id,))

    def __del__(self):
        if self._owned:
            ctx = _context.maybe_ctx()
            if ctx is not None:
                try:
                    ctx.decref(self._id)
                except Exception:
                    pass

    # `await ref` support inside async actors.
    def __await__(self):
        def _get():
            ctx = _context.get_ctx()
            return ctx.get_objects([self._id], timeout=None)[0]
        yield
        return _get()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading
        fut: concurrent.futures.Future = concurrent.futures.Future()
        ref = self

        def _run():
            ctx = _context.get_ctx()
            try:
                fut.set_result(ctx.get_objects([ref._id], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        threading.Thread(target=_run, daemon=True).start()
        return fut


def _reconstruct_borrowed(object_id: str) -> ObjectRef:
    return ObjectRef(object_id, owned=False)


class ActorID:
    __slots__ = ("_id",)

    def __init__(self, actor_id: str):
        self._id = actor_id

    def hex(self) -> str:
        return self._id

    def __repr__(self) -> str:
        return f"ActorID({self._id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ActorID) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)
