"""ObjectRef: the distributed future handle.

Parity: reference python/ray/_raylet.pyx ObjectRef + C++ reference counting
(src/ray/core_worker/reference_count.cc). The protocol is centralized (the
head's controller owns all refcounts) with a real borrower protocol
(reference reference_count.h:64,115-117 borrower registration +
WaitForRefRemoved):

- Deserializing a ref ANYWHERE registers a borrow (ADDREF) and the
  borrowing process sends a deferred DECREF when its copy is collected —
  so an actor may store a ref it received inside an argument past the
  carrying task and the object stays alive until the actor drops it.
- The submit-time pin covers the window before the borrow registers:
  the executing worker's ADDREF and the task's TASK_DONE (which releases
  the pin) travel the same FIFO connection, so the count can never dip
  to zero between them.
- Objects CONTAINING refs (a put() of a list of refs, a task returning
  refs) register containment at seal time: the enclosing object holds a
  count on each inner ref, released when the enclosing object is
  deleted (reference reference_count.cc nested-ref ownership).

Known conservatism: a borrowing worker that is SIGKILLed never sends its
deferred DECREF, so its borrows leak until session shutdown (the
reference reclaims these via per-borrower death cleanup). Decrefs
deferred while NO context is installed park in a BOUNDED set and drain
on the next context attach (r16; see ``_PARK_MAX`` below) — previously
they parked unbounded until session shutdown.
"""
from __future__ import annotations

import collections
import threading
from typing import Optional

from ray_tpu._private import context as _context

# Serialize-time containment capture: object_store.serialize() installs a
# collector here; ObjectRef.__reduce__ records every ref pickled into the
# enclosing object so the store can register containment at seal.
_capture = threading.local()

# Deferred decrefs: __del__ may fire during GC at ANY allocation point —
# including while the current thread holds a non-reentrant lock that the
# decref's deletion path needs (store lock, connection send lock), a
# guaranteed self-deadlock. So __del__ only appends the id here; a
# dedicated flusher thread performs the actual decref (the reference
# defers destructor work to the core worker's io service the same way).
#
# Parked-set bound (r16): while NO context is installed (shutdown /
# re-init gap, or a process that dropped refs before ever attaching),
# the ids PARK here. Unbounded parking was the documented borrow leak —
# a context-less process collecting refs forever grew this deque until
# session end. Past _PARK_MAX the flusher trims the OLDEST parked ids
# (their owner-side counts leak, counted in `dropped_parked`, the same
# conservative direction as a SIGKILLed borrower); everything still
# parked drains the moment a context attaches (context.set_ctx wakes
# the flusher).
_PARK_MAX = 65_536
_deferred: collections.deque = collections.deque()
dropped_parked = 0
_flush_wake = threading.Event()
_flusher_started = False
_flusher_lock = threading.Lock()

# Release hooks (r18): caches keyed by object id — the direct actor
# plane's inline-reply result cache — register here so a ref's release
# also drops the cached value. Invoked on the flusher thread with each
# drained id batch BEFORE the owner-side decref, so a hook never sees
# an id whose owner-side count it could revive.
_release_hooks: list = []


def register_release_hook(fn) -> None:
    """Register fn(object_ids) to run for every flushed decref batch.
    Process-lifetime registration (callers are per-process singletons
    like the direct actor caller's inline-result cache)."""
    _release_hooks.append(fn)


def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    with _flusher_lock:
        if _flusher_started:
            return
        _flusher_started = True
    threading.Thread(target=_flush_loop, name="rtpu-decref",
                     daemon=True).start()


def _flush_loop() -> None:
    global dropped_parked
    while True:
        if not _deferred:
            _flush_wake.wait(0.2)
            _flush_wake.clear()
            continue
        ctx = _context.maybe_ctx()
        if ctx is None:
            # No context (shutdown / re-init gap): leave the ids parked
            # — popping here would leak the owner-side count forever.
            # set_ctx wakes us the moment a new context installs and
            # the parked backlog drains first thing. The set is
            # BOUNDED (r16): trim the oldest past _PARK_MAX so a
            # context-less process cannot grow it for its lifetime.
            while len(_deferred) > _PARK_MAX:
                try:
                    _deferred.popleft()
                    dropped_parked += 1
                except IndexError:
                    break
            _flush_wake.wait(0.2)
            _flush_wake.clear()
            continue
        # Drain in batches: one DECREF_BATCH frame instead of N DECREF
        # frames (context impls without a wire hop just loop locally).
        # The configured cap is clamped to 64, the wire's structural-
        # encoding bound for language-neutral id lists.
        from ray_tpu._private.config import CONFIG
        cap = min(64, max(1, int(CONFIG.wire_batch_max_frames)))
        batch: list[str] = []
        while len(batch) < cap:
            try:
                batch.append(_deferred.popleft())
            except IndexError:
                break
        for hook in _release_hooks:
            try:
                hook(batch)
            except Exception:
                pass
        try:
            ctx.decref_batch(batch)
        except Exception:
            pass


class ObjectRef:
    __slots__ = ("_id", "_owned", "__weakref__")

    def __init__(self, object_id: str, owned: bool = True):
        self._id = object_id
        self._owned = owned

    @property
    def object_id(self) -> str:
        return self._id

    def hex(self) -> str:
        return self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __reduce__(self):
        ids = getattr(_capture, "ids", None)
        if ids is not None:
            ids.append(self._id)
        return (_reconstruct_borrowed, (self._id,))

    def __del__(self):
        if self._owned and _context.maybe_ctx() is not None:
            # never decref synchronously from a destructor (see
            # _deferred above); deque.append is GC-reentrancy-safe
            _deferred.append(self._id)
            _flush_wake.set()
            _ensure_flusher()

    # `await ref` support inside async actors.
    def __await__(self):
        def _get():
            ctx = _context.get_ctx()
            return ctx.get_objects([self._id], timeout=None)[0]
        yield
        return _get()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading
        fut: concurrent.futures.Future = concurrent.futures.Future()
        ref = self

        def _run():
            ctx = _context.get_ctx()
            try:
                fut.set_result(ctx.get_objects([ref._id], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        threading.Thread(target=_run, daemon=True).start()
        return fut


def _reconstruct_borrowed(object_id: str) -> ObjectRef:
    """Deserialization endpoint: register a borrow with the owner (the
    head) so the ref counts while this process holds it; the ref's
    __del__ sends the matching deferred decref. Falls back to a
    non-counting ref in processes without a runtime context (e.g. a
    relaying node agent)."""
    ctx = _context.maybe_ctx()
    if ctx is not None:
        try:
            ctx.addref(object_id)
            return ObjectRef(object_id, owned=True)
        except Exception:
            pass
    return ObjectRef(object_id, owned=False)


class ActorID:
    __slots__ = ("_id",)

    def __init__(self, actor_id: str):
        self._id = actor_id

    def hex(self) -> str:
        return self._id

    def __repr__(self) -> str:
        return f"ActorID({self._id})"

    def __eq__(self, other) -> bool:
        return isinstance(other, ActorID) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)
