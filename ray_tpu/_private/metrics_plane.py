"""Cluster metrics plane (r11): runtime-instrumented time series,
cluster-wide scrape, and the latency signals consumers read.

The r9 tracing plane answers "what happened to this task"; this module
answers "what is the cluster doing right now". Three pieces:

1. **Runtime instrumentation** — first-class runtime series registered
   through the existing :mod:`ray_tpu.util.metrics` API in every
   process. Two source shapes:

   * *Live histograms*, observed at event time because they cannot be
     reconstructed later: task latency split by phase — queue wait
     (from the scheduler's ``_queued_at`` stamp, observed at dispatch),
     exec (worker-side), e2e submit→done (head-side) — each an
     O(log buckets) observe behind one memoized :func:`enabled` gate.
   * *Sampled mirrors* of the plain int counters the hot paths already
     keep (``protocol.WIRE_STATS``/``POLLER_STATS``,
     ``OBJECT_PLANE_STATS``, shm ``SEGMENT_POOL``, delegated-lease
     ledgers): gauges refreshed by per-process **samplers** only when a
     scrape happens, so the hot paths never touch a metrics lock.

   ``RAY_TPU_METRICS=0`` disables everything: no series are ever
   registered and every observe short-circuits on the gate — zero
   metric bytes, the ``RAY_TPU_TRACE=0`` discipline.

2. **Cluster collection** — pull-based, like ``trace_dump``: the head
   fans a ``METRICS_DUMP`` frame to its local workers and every agent
   (agents drain their own workers off the poller thread and reply
   with the whole node), then merges the per-process registry
   snapshots with ``node``/``worker`` labels. Histogram series merge
   by summing aligned buckets; sources that stop answering expire
   after ``RAY_TPU_METRICS_TTL_S`` so removed workers/nodes cannot
   linger in ``/metrics`` forever. The head keeps a short retention
   ring of per-scrape aggregates for dashboard sparklines and windowed
   latency signals.

3. **Consumers** — the dashboard's ``/metrics`` exposition switches
   from head-local to cluster-aggregated, ``/api/metrics_summary``
   serves the JSON view, and the autoscaler reads
   :meth:`ClusterCollector.queue_wait_p95` as its queue-latency
   scale-up signal (``RAY_TPU_AUTOSCALE_QUEUE_LATENCY_S``).

Reference parity: the reference runtime ships per-component
OpenCensus metrics through each raylet to a head-side exporter
(src/ray/stats/metric_defs.cc + dashboard/modules/reporter); here the
transport is the existing control wire and the registry is our own.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu._private import tracing_plane as _tp

# --------------------------------------------------------------- gate
# (gen, enabled): memoized per CONFIG generation — the per-emission
# gate costs a tuple index, not an env lookup (same discipline as
# tracing_plane.enabled / native.frame_engine_enabled).
_state: tuple = (-1, False)


def enabled() -> bool:
    global _state
    from ray_tpu._private.config import CONFIG
    gen = CONFIG._gen
    st = _state
    if st[0] == gen:
        return st[1]
    _state = (gen, bool(CONFIG.metrics))
    return _state[1]


# -------------------------------------------------- runtime series
# Latency histograms share the registry's default boundaries
# (1 ms … 60 s): queue waits and exec times in this runtime span that
# whole range, and identical boundaries everywhere make the cluster
# merge exact bucket-for-bucket.
class _RuntimeMetrics:
    """The runtime's own series, registered lazily on first use while
    the plane is enabled — a process that never emits (or runs with
    RAY_TPU_METRICS=0) never registers anything."""

    def __init__(self):
        from ray_tpu._private.config import CONFIG
        from ray_tpu.util.metrics import (
            DEFAULT_HISTOGRAM_BOUNDARIES, DEFAULT_REGISTRY, Gauge,
            Histogram)
        reg = DEFAULT_REGISTRY
        # quantile() resolves at bucket granularity, so the p95-vs-
        # threshold comparison is only exact AT a bucket bound: when
        # the autoscale threshold is configured, make it one (every
        # process sees the same env, keeping the cluster merge
        # aligned; a straggler still merges via the union-of-bounds
        # fallback). Boundaries are immutable once a series exists, so
        # the threshold is captured at this process's FIRST registry
        # use — set the env before init; changing it via a later
        # CONFIG.reload() moves the trigger but p95 then resolves at
        # the nearest pre-existing bound.
        qw_bounds = set(DEFAULT_HISTOGRAM_BOUNDARIES)
        if CONFIG.autoscale_queue_latency_s > 0:
            qw_bounds.add(float(CONFIG.autoscale_queue_latency_s))
        self.queue_wait = Histogram(
            "ray_tpu_task_queue_wait_s",
            "Task queue wait: enqueue to dispatch, per scheduler node",
            boundaries=sorted(qw_bounds), tag_keys=("node",),
            registry=reg)
        self.exec = Histogram(
            "ray_tpu_task_exec_s",
            "Task execution wall time (worker-side)", registry=reg)
        self.e2e = Histogram(
            "ray_tpu_task_e2e_s",
            "Task end-to-end: submit to head-side done, per executing "
            "node", tag_keys=("node",), registry=reg)
        g = lambda name, desc, tags=(): Gauge(  # noqa: E731
            name, desc, tag_keys=tags, registry=reg)
        self.wire = g("ray_tpu_wire_frames",
                      "Process socket frames/messages (WIRE_STATS "
                      "mirror)", ("counter",))
        self.poller = g("ray_tpu_poller",
                        "Shared read-loop stats: passes, frames, "
                        "bytes, busy_ms, max_pass_ms", ("counter",))
        self.object_plane = g("ray_tpu_object_plane",
                              "Object-plane counters (pulls, serves, "
                              "dedup hits, bytes)", ("counter",))
        self.pull_inflight = g("ray_tpu_pull_inflight",
                               "Pull-manager in-flight transfers")
        self.pull_inflight_bytes = g("ray_tpu_pull_inflight_bytes",
                                     "Pull-manager in-flight bytes")
        self.shm_pool = g("ray_tpu_shm_pool",
                          "shm segment pool: bytes, segments, reused, "
                          "misses, released", ("counter",))
        self.lease_outstanding = g(
            "ray_tpu_lease_outstanding",
            "Delegated tasks granted to an agent and not yet reported "
            "done (head-side ledger)", ("node",))
        self.lease_batches = g(
            "ray_tpu_lease_batches",
            "NODE_LEASE_BATCH frames sent per agent", ("node",))
        self.lease_tasks = g(
            "ray_tpu_tasks_leased",
            "Tasks granted via bulk leases per agent", ("node",))
        self.lease_revoked = g(
            "ray_tpu_lease_revoked",
            "Delegated tasks reclaimed by revoke/steal, as reported "
            "by each agent", ("node",))
        self.delegate = g("ray_tpu_delegate",
                          "Agent-side delegated-lease counters",
                          ("counter",))
        self.head_wal = g("ray_tpu_head_wal",
                          "Head-HA WAL telemetry (r15): wal_bytes/"
                          "records/fsyncs, fsync_p99_ms, compactions, "
                          "last_snapshot_age_s, replayed/deduped "
                          "completion counts", ("counter",))
        self.head_shard = g(
            "ray_tpu_head_shard",
            "Striped head-table occupancy/contention (r16): entries, "
            "max_stripe, contended lock acquisitions per table — "
            "proves the stripes spread load", ("table", "counter"))
        self.decref_delta = g(
            "ray_tpu_decref_delta",
            "Batched decref-delta counters (r16): agent-side frames/"
            "entries/releases coalesced (plus buffered + forwarded "
            "fallbacks); head-side frames/entries applied and "
            "replayed frames deduped", ("counter",))
        self.direct_actor = g(
            "ray_tpu_direct_actor",
            "Direct actor call plane counters (r18): caller-side "
            "direct calls/replies/inline bytes/fallbacks/redirects/"
            "resolves, host-side served/nacks/served bytes, and the "
            "head's head-routed-send + mirror-delta counts",
            ("party", "counter"))
        self.node_liveness = g(
            "ray_tpu_node_liveness",
            "Per-node liveness (r17): 1 for the node's current state "
            "(alive / suspect / draining / dead)", ("node", "state"))
        self.node_heartbeat_age = g(
            "ray_tpu_node_heartbeat_age_s",
            "Seconds since each node's last heartbeat (r17 liveness "
            "plane)", ("node",))
        self.membership = g(
            "ray_tpu_membership",
            "Partition-tolerant membership counters (r17): suspected/"
            "recovered/deaths/fenced node transitions, fenced frames "
            "dropped, fence notices sent, stale-attempt terminal "
            "drops", ("counter",))
        self.channel = g(
            "ray_tpu_channel",
            "Wire-channel ring telemetry (r13/r20): tx/rx frame and "
            "logical read/write counts, writer_block_ms (time writers "
            "spent waiting on reader acks — ring pressure), "
            "reader_wait_ms, plus live ring occupancy; the staleness "
            "signal the Sebulba RL subsystem tunes against",
            ("counter",))
        self.rl = g(
            "ray_tpu_rl",
            "Sebulba RL counters (r20): env steps, trajectory shards "
            "written/consumed, inference requests/forwards/batched "
            "obs, weight publishes, learner version, staleness, "
            "failovers", ("counter",))


class _ServingMetrics:
    """Serving-plane series (r19 LLM engine): registered lazily like
    the runtime set, but only in processes that actually serve —
    importing the engine in a process that never generates registers
    nothing."""

    def __init__(self):
        from ray_tpu.util.metrics import (Counter, DEFAULT_REGISTRY,
                                          Histogram)
        reg = DEFAULT_REGISTRY
        # Token-level latencies live well under the default 1 ms …
        # 60 s task boundaries' useful range, so give TTFT/TPOT their
        # own sub-millisecond-to-seconds ladder.
        bounds = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                  0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0]
        self.ttft = Histogram(
            "ray_tpu_llm_ttft_s",
            "LLM time-to-first-token: submit to first emitted token "
            "(engine-side, includes queue wait + prefill)",
            boundaries=bounds, registry=reg)
        self.tpot = Histogram(
            "ray_tpu_llm_tpot_s",
            "LLM time-per-output-token: inter-token gap during decode",
            boundaries=bounds, registry=reg)
        self.tokens = Counter(
            "ray_tpu_llm_tokens",
            "LLM tokens emitted by this engine replica", registry=reg)


_mx: Optional[_RuntimeMetrics] = None
_mx_lock = threading.Lock()
_sv: Optional[_ServingMetrics] = None


def serving_metrics() -> Optional[dict]:
    """TTFT/TPOT histograms + token counter for the LLM engine, or
    None while the plane is disabled (callers skip their observes)."""
    if not enabled():
        return None
    global _sv
    m = _sv
    if m is None:
        with _mx_lock:
            m = _sv
            if m is None:
                _sv = m = _ServingMetrics()
    return {"ttft": m.ttft, "tpot": m.tpot, "tokens": m.tokens}


def _metrics() -> _RuntimeMetrics:
    global _mx
    m = _mx
    if m is None:
        with _mx_lock:
            m = _mx
            if m is None:
                _mx = m = _RuntimeMetrics()
    return m


# ------------------------------------------------ hot-path observes
def observe_queue_wait(seconds: float, node_id: str) -> None:
    """Scheduler dispatch: enqueue → lease, from the `_queued_at`
    stamp the queue already keeps (enqueue pays nothing)."""
    if enabled():
        _metrics().queue_wait.observe(seconds, {"node": node_id})


def observe_exec(seconds: float) -> None:
    """Worker-side task execution wall time."""
    if enabled():
        _metrics().exec.observe(seconds)


def submit_stamp(spec) -> None:
    """Head-side submit: stamp the spec so the done path can observe
    e2e without a lookup (the attribute survives the agent round-trip
    because the head keeps the mirrored spec object)."""
    if enabled():
        spec._submit_mono = time.monotonic()


def observe_task_done(spec, node_id: str) -> None:
    """Head-side completion: submit → done, against the submit stamp
    (missing on specs submitted while the plane was disabled)."""
    if not enabled():
        return
    t0 = getattr(spec, "_submit_mono", None)
    if t0 is not None:
        _metrics().e2e.observe(time.monotonic() - t0,
                               {"node": node_id or ""})


# ---------------------------------------------------------- samplers
# Per-process refresh hooks that copy the hot paths' plain int
# counters into registry gauges at SCRAPE time. Keyed by name so a
# re-created owner (tests start/stop runtimes in one process)
# replaces its predecessor instead of stacking.
_samplers: Dict[str, Callable[[], None]] = {}
_samplers_lock = threading.Lock()


def set_sampler(name: str, fn: Optional[Callable[[], None]]) -> None:
    with _samplers_lock:
        if fn is None:
            _samplers.pop(name, None)
        else:
            _samplers[name] = fn


def _builtin_sampler() -> None:
    """Process-agnostic mirrors: wire/poller frame counters, object-
    plane counters, shm pool — all module-level plain dicts that exist
    in every runtime process."""
    from ray_tpu._private import protocol
    from ray_tpu._private.object_store import SEGMENT_POOL
    from ray_tpu._private.object_transfer import OBJECT_PLANE_STATS
    m = _metrics()
    m.wire.set_many([({"counter": k}, v)
                     for k, v in protocol.WIRE_STATS.items()])
    ps = protocol.POLLER_STATS
    m.poller.set_many([
        ({"counter": "passes"}, ps["passes"]),
        ({"counter": "frames"}, ps["frames"]),
        ({"counter": "bytes"}, ps["bytes"]),
        ({"counter": "busy_ms"}, ps["busy_ns"] / 1e6),
        ({"counter": "max_pass_ms"}, ps["max_pass_ns"] / 1e6),
    ])
    m.object_plane.set_many([({"counter": k}, v)
                             for k, v in OBJECT_PLANE_STATS.items()])
    m.shm_pool.set_many([({"counter": k.replace("pool_", "")}, v)
                         for k, v in SEGMENT_POOL.stats().items()])
    # Optional planes: mirror only in processes that imported them
    # (sys.modules guard — a scrape must not trigger heavy imports).
    wc = sys.modules.get("ray_tpu.experimental.wire_channel")
    if wc is not None:
        st = wc.CH_STATS
        rows = [({"counter": k}, v) for k, v in st.items()
                if not k.endswith("_ns")]
        rows += [({"counter": "writer_block_ms"},
                  st["writer_block_ns"] / 1e6),
                 ({"counter": "reader_wait_ms"},
                  st["reader_wait_ns"] / 1e6)]
        rows += [({"counter": k}, v)
                 for k, v in wc.ring_stats().items()]
        m.channel.set_many(rows)
    sb = sys.modules.get("ray_tpu.rllib.sebulba.stats")
    if sb is not None:
        m.rl.set_many([({"counter": k}, v)
                       for k, v in sb.RL_STATS.items()])


def run_samplers() -> None:
    if not enabled():
        return
    try:
        _builtin_sampler()
    except Exception:
        pass
    with _samplers_lock:
        fns = list(_samplers.values())
    for fn in fns:
        try:
            fn()
        except Exception:
            pass        # a broken sampler must never break a scrape


# --------------------------------------------------------- snapshots
def local_dump() -> dict:
    """This process's registry snapshot (samplers refreshed), shaped
    for the METRICS_DUMP pull protocol."""
    if not enabled():
        return {"enabled": False, "metrics": {}}
    run_samplers()
    from ray_tpu.util.metrics import DEFAULT_REGISTRY
    return {"enabled": True, "pid": os.getpid(),
            "role": _tp._role, "name": _tp._role_name,
            "metrics": DEFAULT_REGISTRY.collect()}


def _cdf_at(buckets: tuple, bound: float) -> int:
    """Cumulative count of a histogram's bucket tuple at `bound`: the
    count of the greatest bound <= it (the exact step-function read of
    a CDF over sorted boundaries). The one reader both the cluster
    merge and the windowed delta use, so they cannot drift."""
    best = 0
    for bo, c in buckets:
        if bo <= bound:
            best = c
        else:
            break
    return best


def _merge_hist(a: tuple, b: tuple) -> tuple:
    """Sum two cumulative histogram values. Aligned boundaries (the
    overwhelmingly common case: every process registers the same
    series definition) sum bucket-for-bucket; differing boundary sets
    merge on the union via the CDF step read."""
    ta, ca, ba = a
    tb, cb, bb = b
    if len(ba) == len(bb) and all(x[0] == y[0]
                                  for x, y in zip(ba, bb)):
        buckets = tuple((x[0], x[1] + y[1]) for x, y in zip(ba, bb))
        return (ta + tb, ca + cb, buckets)
    bounds = sorted({bo for bo, _ in ba} | {bo for bo, _ in bb})
    return (ta + tb, ca + cb,
            tuple((bo, _cdf_at(ba, bo) + _cdf_at(bb, bo))
                  for bo in bounds))


def hist_delta(new: tuple, old: tuple) -> tuple:
    """new - old for cumulative histogram values (windowed
    distributions from two ring samples). Boundary sets usually match;
    when the cluster merge's union-of-bounds fallback introduced a
    bound absent from `old`, read old's CDF at the greatest bound <=
    it — treating it as 0 would count every pre-window observation
    below the new bound as in-window."""
    tn, cn, bn = new
    to, co, bo = old
    return (tn - to, max(0, cn - co),
            tuple((b, max(0, c - _cdf_at(bo, b))) for b, c in bn))


def quantile(hist_value: Optional[tuple], q: float) -> Optional[float]:
    """Bucket-resolution quantile estimate of a cumulative histogram
    value: the upper bound of the first bucket whose cumulative count
    covers rank q (inf when the rank falls past the last bound; None
    when the histogram is empty)."""
    if not hist_value:
        return None
    total, count, buckets = hist_value
    if count <= 0:
        return None
    rank = q * count
    for b, c in buckets:
        if c >= rank:
            return float(b)
    return float("inf")


def prune_node_series(expired: set) -> None:
    """Drop this process's runtime histogram series tagged with
    cluster nodes that have TTL-expired: under node churn (the
    autoscaler's whole purpose) the head's e2e/queue-wait histograms
    would otherwise grow one dead series per retired node forever.
    Sampled gauges already self-clean via set_many replace-all."""
    m = _mx
    if m is None or not expired:
        return
    pred = lambda key: dict(key).get("node") in expired  # noqa: E731
    m.queue_wait.prune_series(pred)
    m.e2e.prune_series(pred)


def merge_dumps(entries: Sequence[dict]) -> dict:
    """Merge per-process registry snapshots into one cluster snapshot.

    Each entry is ``{"labels": {"node": ..., "worker": ...},
    "metrics": <registry collect()>}``. Every series key is extended
    with the entry's labels — except labels the metric already tags
    itself with (e.g. the queue-wait histogram carries its scheduler's
    ``node``, which for in-process nodes differs from the process's) —
    so per-process series stay distinguishable; series that still
    collide (same tags from two sources, e.g. an agent-tagged
    histogram observed in two processes) merge by type: histograms sum
    aligned buckets, counters add, gauges keep the last value."""
    merged: Dict[str, dict] = {}
    for e in entries:
        labels = e.get("labels") or {}
        for name, snap in (e.get("metrics") or {}).items():
            m = merged.get(name)
            if m is None:
                m = merged[name] = {"type": snap["type"],
                                    "description":
                                        snap.get("description", ""),
                                    "series": {}}
            elif m["type"] != snap["type"]:
                continue            # name clash across types: skip
            for tags, value in snap["series"].items():
                have = {k for k, _ in tags}
                key = tags + tuple(
                    (k, str(v)) for k, v in sorted(labels.items())
                    if k not in have)
                cur = m["series"].get(key)
                if cur is None:
                    m["series"][key] = value
                elif m["type"] == "histogram":
                    m["series"][key] = _merge_hist(cur, value)
                elif m["type"] == "counter":
                    m["series"][key] = cur + value
                else:
                    m["series"][key] = value
    return merged


def aggregate_histogram(merged: dict, name: str) -> Optional[tuple]:
    """Sum every series of one histogram metric into a single
    cluster-wide (total, count, buckets) value."""
    snap = merged.get(name)
    if not snap or snap.get("type") != "histogram":
        return None
    out: Optional[tuple] = None
    for value in snap["series"].values():
        out = value if out is None else _merge_hist(out, value)
    return out


def prometheus_text(merged: dict) -> str:
    from ray_tpu.util.metrics import render_prometheus
    return render_prometheus(merged)


# ------------------------------------------------- cluster collector
class ClusterCollector:
    """Head-side scrape fan-out + merge + retention.

    ``collect()`` requests every process's registry snapshot under one
    shared deadline (the tracing plane's fan-out machinery, with
    METRICS_DUMP), folds the replies into a source cache keyed by
    (node, worker), and merges every source seen within
    ``RAY_TPU_METRICS_TTL_S`` — one missed reply doesn't flap the
    exposition, and a removed worker/node expires instead of
    lingering. Each collection appends one aggregate sample to the
    retention ring (``RAY_TPU_METRICS_RING``) that the dashboard
    sparklines and the autoscaler's windowed p95 read. Collections are
    rate-limited by ``RAY_TPU_METRICS_MIN_SCRAPE_S``: concurrent
    pullers (Prometheus + dashboard + autoscaler) share one fan-out.
    """

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._collecting = False
        # (node_id, worker_id) -> (monotonic_ts, labels, metrics)
        self._sources: Dict[tuple, tuple] = {}
        # node_id -> last monotonic ts the node was seen ALIVE: series
        # labeled with a node past its TTL are filtered even when they
        # live in a healthy process's registry (the head's own e2e
        # histogram tags the executing node, which may be long dead).
        # Only ids that were EVER cluster nodes are subject to the
        # filter — a user metric may tag "node" with its own values.
        self._node_seen: Dict[str, float] = {}
        self._node_ever: set = set()
        self._ring: deque = deque(maxlen=4096)
        self._last_collect = 0.0
        self._last_merged: Optional[dict] = None

    # ------------------------------------------------------ scrape
    def collect(self, timeout: float = 3.0) -> dict:
        """Cluster-merged registry snapshot (rate-limited fan-out)."""
        from ray_tpu._private.config import CONFIG
        if not enabled():
            return {}
        now = time.monotonic()
        with self._lock:
            fresh = (self._last_merged is not None
                     and now - self._last_collect
                     < max(0.0, CONFIG.metrics_min_scrape_s))
            if fresh:
                return self._last_merged
            if self._collecting:
                # a fan-out is already in flight (a slow gather can
                # outlive the rate-limit window): wait for its result
                # instead of doubling the cluster dump traffic
                self._cv.wait(timeout)
                return self._last_merged or {}
            self._collecting = True
            self._last_collect = now    # claim before the slow fan-out
        try:
            entries = self._gather(timeout)
            alive_nodes = {n.node_id for n in
                           self._rt.cluster.alive_nodes()}
            alive_nodes.add(self._rt.head_node_id)
            # every id the cluster has EVER registered (dead records
            # included) is subject to node-TTL filtering below
            ever_ids = {n.node_id for n in self._rt.cluster.nodes()}
            now = time.monotonic()
            ttl = max(0.0, CONFIG.metrics_ttl_s)
            # source-table bookkeeping is cheap — take the lock for it,
            # but run the O(total-series) merge/filter OUTSIDE so
            # concurrent ring()/stats()/_windowed() readers never stall
            # behind a large-cluster merge (safe: `_collecting` makes
            # this body single-flight, so nothing else mutates
            # _sources/_node_* between the two lock sections)
            with self._lock:
                for key, labels, metrics in entries:
                    self._sources[key] = (now, labels, metrics)
                alive = {}
                for key, (ts, labels, metrics) in self._sources.items():
                    if now - ts <= ttl:
                        alive[key] = (ts, labels, metrics)
                self._sources = alive
                self._node_ever.update(ever_ids)
                self._node_ever.update(alive_nodes)
                for nid in alive_nodes:
                    self._node_seen[nid] = now
                self._node_seen = {nid: ts for nid, ts
                                   in self._node_seen.items()
                                   if now - ts <= ttl}
                keep = set(self._node_seen)
                ever = set(self._node_ever)
            merged = merge_dumps([
                {"labels": labels, "metrics": metrics}
                for ts, labels, metrics in alive.values()])
            # node-level expiry: a dead node's series vanish after
            # the TTL even when a healthy process's registry still
            # tags them (head-side e2e labels the EXECUTING node).
            # Only ids that were ever cluster nodes are filtered —
            # user metrics may tag "node" with foreign values.
            prune_node_series(ever - keep)
            for snap in merged.values():
                kept = {}
                for k, v in snap["series"].items():
                    n = dict(k).get("node")
                    if n in (None, "") or n not in ever or n in keep:
                        kept[k] = v
                snap["series"] = kept
            sample = self._sample(merged)
            with self._lock:
                self._last_merged = merged
                ring_cap = int(CONFIG.metrics_ring)
                if ring_cap > 0:
                    if self._ring.maxlen != ring_cap:
                        self._ring = deque(self._ring, maxlen=ring_cap)
                    self._ring.append(sample)
        finally:
            with self._lock:
                self._collecting = False
                self._cv.notify_all()
        return merged

    def _gather(self, timeout: float) -> List[tuple]:
        """[(source_key, labels, metrics), ...] for every process that
        answered: the head's own registry, its local workers, and each
        agent (which drains its own workers)."""
        from ray_tpu._private import protocol
        rt = self._rt
        head_nid = rt.head_node_id
        out: List[tuple] = [
            ((head_nid, ""), {"node": head_nid, "worker": ""},
             local_dump().get("metrics") or {})]
        targets: List[tuple] = []
        sched = rt.scheduler
        if sched is not None:
            for wid, conn in sched.worker_conns():
                targets.append((("worker", head_nid, wid), conn))
        for node in rt.cluster.alive_nodes():
            nsched = node.scheduler
            conn = getattr(nsched, "conn", None)
            if conn is not None and conn.peer_speaks_metrics():
                targets.append((("agent", node.node_id, ""), conn))
            elif (node.node_id != head_nid
                  and hasattr(nsched, "worker_conns")):
                # in-process (cluster-sim) node: no agent process to
                # drain it — fan to its subprocess workers directly
                for wid, wconn in nsched.worker_conns():
                    targets.append((("worker", node.node_id, wid),
                                    wconn))
        for (kind, nid, wid), t0, t1, rep in _tp.fanout_dumps(
                targets, timeout, extra={"timeout": timeout},
                mtype=protocol.METRICS_DUMP):
            if kind == "worker":
                d = rep.get("dump") or {}
                if d.get("metrics"):
                    out.append(((nid, wid),
                                {"node": nid, "worker": wid},
                                d["metrics"]))
            else:
                for d in rep.get("processes") or ():
                    if not d.get("metrics"):
                        continue
                    w = d.get("worker", "")
                    out.append(((nid, w), {"node": nid, "worker": w},
                                d["metrics"]))
        return out

    # --------------------------------------------------- retention
    @staticmethod
    def _gauge_total(merged: dict, name: str,
                     counter: Optional[str] = None) -> float:
        snap = merged.get(name)
        if not snap:
            return 0.0
        total = 0.0
        for tags, v in snap["series"].items():
            if counter is not None and ("counter", counter) not in tags:
                continue
            try:
                total += float(v)
            except (TypeError, ValueError):
                pass
        return total

    def _sample(self, merged: dict) -> dict:
        """One retention-ring entry: cumulative cluster aggregates
        (subtractable, so consumers derive windowed distributions and
        rates from any two samples)."""
        e2e = aggregate_histogram(merged, "ray_tpu_task_e2e_s")
        return {
            "ts": time.time(),
            "mono": time.monotonic(),
            "queue_wait": aggregate_histogram(
                merged, "ray_tpu_task_queue_wait_s"),
            "exec": aggregate_histogram(merged, "ray_tpu_task_exec_s"),
            "e2e": e2e,
            "tasks_done": int(e2e[1]) if e2e else 0,
            "wire_frames": self._gauge_total(
                merged, "ray_tpu_wire_frames", "tx_frames")
                + self._gauge_total(
                    merged, "ray_tpu_wire_frames", "rx_frames"),
            "pull_inflight_bytes": self._gauge_total(
                merged, "ray_tpu_pull_inflight_bytes"),
            "sources": len(self._sources),
        }

    def ring(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # ----------------------------------------------------- signals
    def _windowed(self, phase: str, window_s: float) -> Optional[tuple]:
        """Cluster histogram delta over the last `window_s`: newest
        sample minus the cluster state AT the window start (the latest
        sample older than the cutoff). When the ring doesn't reach
        back that far the process-lifetime cumulative value stands in
        — everything recorded is "recent" from the ring's view."""
        with self._lock:
            samples = list(self._ring)
        if not samples:
            return None
        newest = samples[-1]
        cur = newest.get(phase)
        if cur is None:
            return None
        base = None
        cutoff = newest["mono"] - window_s
        for s in samples[:-1]:
            if s["mono"] >= cutoff:
                break
            if s.get(phase) is not None:
                base = s[phase]     # latest sample BEFORE the cutoff
        return cur if base is None else hist_delta(cur, base)

    def _collect_async(self, timeout: float) -> None:
        """Kick a collect on its own thread unless one is fresh or
        already in flight (collect() re-checks both under its lock, so
        the unlocked peek here only avoids pointless thread spawns)."""
        from ray_tpu._private.config import CONFIG
        fresh = (self._last_merged is not None
                 and time.monotonic() - self._last_collect
                 < max(0.0, CONFIG.metrics_min_scrape_s))
        if fresh or self._collecting:
            return
        threading.Thread(target=self.collect, kwargs={"timeout": timeout},
                         name="rtpu-metrics-collect", daemon=True).start()

    def queue_wait_p95(self, window_s: Optional[float] = None,
                       timeout: float = 2.0,
                       block: bool = True) -> Optional[float]:
        """Cluster task queue-wait p95 over the recent window — the
        autoscaler's latency signal. Triggers a (rate-limited) collect
        so a 1 Hz caller keeps the ring warm on its own; None when no
        tasks waited in the window. ``block=False`` kicks the fan-out
        on a background thread and reads the newest ring sample — a
        wedged agent then costs signal freshness, never the caller's
        loop (the autoscaler's reconcile tick also drives demand
        scaling and launch bookkeeping)."""
        from ray_tpu._private.config import CONFIG
        if not enabled():
            return None
        if window_s is None:
            window_s = CONFIG.autoscale_queue_latency_window_s
        if block:
            self.collect(timeout=timeout)
        else:
            self._collect_async(timeout)
        return quantile(self._windowed("queue_wait", window_s), 0.95)

    # ----------------------------------------------------- summary
    def summary(self, timeout: float = 3.0) -> dict:
        """JSON view for /api/metrics_summary: latest cluster
        aggregates + per-sample rates for the sparkline ring."""
        from ray_tpu._private.config import CONFIG
        merged = self.collect(timeout=timeout)
        with self._lock:
            samples = list(self._ring)
            n_sources = len(self._sources)
        window = CONFIG.autoscale_queue_latency_window_s

        def pcts(phase: str) -> dict:
            h = self._windowed(phase, window)
            fin = lambda v: (None if v is None or v == float("inf")  # noqa: E731
                             else v)      # keep the JSON strict-valid
            return {"p50": fin(quantile(h, 0.50)),
                    "p95": fin(quantile(h, 0.95)),
                    "p99": fin(quantile(h, 0.99)),
                    "count": int(h[1]) if h else 0}

        spark: List[dict] = []
        for prev, cur in zip(samples, samples[1:]):
            dt = max(1e-6, cur["mono"] - prev["mono"])
            qd = (hist_delta(cur["queue_wait"], prev["queue_wait"])
                  if cur.get("queue_wait") and prev.get("queue_wait")
                  else None)
            q95 = quantile(qd, 0.95)
            # clamp at 0: a TTL-expired node shrinks the cluster
            # cumulative, which is not a negative rate
            spark.append({
                "ts": cur["ts"],
                "tasks_per_s": round(max(
                    0.0, cur["tasks_done"] - prev["tasks_done"]) / dt, 2),
                "queue_p95_ms": (round(q95 * 1e3, 3)
                                 if q95 not in (None, float("inf"))
                                 else None),
                "wire_frames_per_s": round(max(
                    0.0, cur["wire_frames"] - prev["wire_frames"]) / dt, 1),
                "pull_inflight_mb": round(
                    cur["pull_inflight_bytes"] / 2 ** 20, 2),
            })
        shm = merged.get("ray_tpu_shm_pool", {}).get("series", {})
        reused = sum(v for k, v in shm.items()
                     if ("counter", "reused") in k)
        misses = sum(v for k, v in shm.items()
                     if ("counter", "misses") in k)
        return {
            "enabled": enabled(),
            "sources": n_sources,
            "window_s": window,
            "queue_wait": pcts("queue_wait"),
            "exec": pcts("exec"),
            "e2e": pcts("e2e"),
            "tasks_done_total": (samples[-1]["tasks_done"]
                                 if samples else 0),
            "shm_pool_hit_rate": (round(reused / (reused + misses), 3)
                                  if reused + misses else None),
            "lease_outstanding": self._gauge_total(
                merged, "ray_tpu_lease_outstanding"),
            "ring": spark,
        }

    def stats(self) -> dict:
        with self._lock:
            return {"sources": len(self._sources),
                    "ring_len": len(self._ring)}
