"""Standalone node agent: joins a remote head over TCP.

The raylet-equivalent process (reference src/ray/raylet/main.cc): it
registers its resources with the head (reference
gcs/gcs_server/gcs_node_manager.h:62 HandleRegisterNode), runs the real
per-node ``Scheduler`` + worker pool locally, owns a local shm object
store, and serves chunked object pulls so a worker on another host can
read objects produced here (reference object_manager/object_manager.cc).

Topology:
- one control connection agent -> head (registration, heartbeats,
  routed specs, relayed worker control-plane traffic, task-done events);
- a local TCP listener for (a) this node's worker subprocesses and
  (b) object pulls from the head or peer agents;
- on-demand data connections to peer agents for cross-host gets.

Division of labor with the head: placement, actor bookkeeping,
refcounts, the object *directory*, and waiter parking are head-side;
dispatch, the resource ledger, worker lifecycles, and object *bytes*
are agent-side. Small task results are forwarded inline to the head
(owner-inline parity, reference core_worker.h AllocateReturnObject);
large ones stay local and register a location.

Run: ``python -m ray_tpu._private.node_agent --head HOST:PORT
[--num-cpus N] [--num-tpus N] [--resources JSON] [--bind HOST]
[--advertise HOST]``
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

from ray_tpu._private import direct_actor as _da
from ray_tpu._private import metrics_plane as _mp
from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG
from ray_tpu._private.object_store import (LocalStore, StoredObject,
                                           unlink_segment)
from ray_tpu._private.object_transfer import (OBJECT_PLANE_STATS,
                                              PullServer, materialize)
from ray_tpu._private.pull_manager import PullManager
from ray_tpu._private.scheduler import Scheduler
from ray_tpu._private.specs import ActorSpec

import logging

log = logging.getLogger(__name__)

HEARTBEAT_PERIOD_S = 0.5


class _AgentFacade:
    """The tiny runtime interface Scheduler drives; every callback
    becomes a NODE_EVENT to the head."""

    def __init__(self, agent: "NodeAgent"):
        self._agent = agent

    def on_task_dispatched(self, spec, worker_id: str) -> None:
        if spec.task_id in self._agent._lease_of:
            # delegated task (r10): the head is no longer a per-task
            # participant — it learns the terminal state from the
            # coalesced done batch; per-dispatch events are the frames
            # delegation exists to eliminate
            self._agent._delegate_stats["dispatch_events_suppressed"] \
                += 1
            return
        self._agent.send_event("task_dispatched", key=spec.task_id,
                               name=spec.name, worker_id=worker_id)

    def on_actor_dispatched(self, spec, worker_id: str) -> None:
        self._agent.send_event("actor_dispatched",
                               key="actor:" + spec.actor_id,
                               actor_id=spec.actor_id, worker_id=worker_id)

    def on_unplaceable(self, spec, reason: str) -> None:
        # a leased task that can never run here is off this agent's
        # book (the head fails/re-places it from the event) — consume
        # its lease or the ledger entry leaks for the agent's lifetime
        if getattr(spec, "task_id", None):
            self._agent._lease_done(spec.task_id)
        self._agent.send_event("unplaceable", spec=spec, reason=reason)


class NodeAgent:
    def __init__(self, head_addr: tuple[str, int],
                 resources: dict[str, float],
                 labels: Optional[dict] = None,
                 max_workers: Optional[int] = None,
                 bind_host: str = "0.0.0.0",
                 advertise_host: Optional[str] = None,
                 node_id: Optional[str] = None):
        self.head_addr = head_addr
        self.store = LocalStore()
        self._stop = threading.Event()
        # r10: shared epoll/select read loop for every connection this
        # agent owns (head control conn, local workers, peer pullers);
        # None (RAY_TPU_EPOLL=0) restores thread-per-connection.
        self._poller = protocol.make_poller()
        self._fetch_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="rtpu-agent-fetch")
        self._pull_server = PullServer(self.store,
                                       executor=self._fetch_pool)
        # peer agent data connections, keyed by (host, port)
        self._peers: dict[tuple[str, int], protocol.Connection] = {}
        self._peer_lock = threading.Lock()
        # Pull manager (reference pull_manager.cc): dedups concurrent
        # fetches of one object into one transfer, bounds in-flight
        # transfers/bytes, and sources chunks from ANY holder the
        # directory reports — completed pulls register this node as a
        # replica so it can serve its broadcast subtree / later readers.
        self._pull_mgr = PullManager(
            self.store, sources_fn=self._pull_sources,
            on_complete=self._on_pull_complete,
            on_source_failed=self._on_pull_source_failed,
            on_partial=self._on_pull_partial,
            on_partial_failed=self._on_pull_partial_failed)

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((bind_host, 0))
        self._listener.listen(128)
        port = self._listener.getsockname()[1]

        # Scheduler BEFORE registration: the instant the head learns of
        # this node it may route specs here, and the connection reader
        # must have a scheduler to hand them to. The agent mints its own
        # node id for the same reason.
        import uuid as _uuid
        self.node_id = node_id or ("node_" + _uuid.uuid4().hex[:8])
        _tp.set_role("agent", self.node_id)
        self.scheduler = Scheduler(
            _AgentFacade(self), dict(resources),
            ("127.0.0.1", port),   # workers are host-local: loopback
            max_workers, node_id=self.node_id, cluster=None)
        self.scheduler.start()

        # head-reconnect state (reference: raylets tolerate GCS downtime
        # and re-register on GCS restart)
        self._reconnect_lock = threading.Lock()
        self._reconnecting = False
        self._fencing = False          # r17 fence reset in progress
        self.incarnation = 0           # r17 epoch (set at register)
        self._pending_relays: list = []          # (conn, msg) to replay
        # state-bearing fire-and-forget messages (task completions,
        # object locations, worker deaths) that failed during a head
        # outage — replayed on rejoin so results produced while the head
        # was down are not silently lost
        import collections as _collections
        self._pending_sends: _collections.deque = _collections.deque(
            maxlen=10_000)
        self._dropped_sends = 0
        # ---- delegated bulk leases (r10) ----
        # task_id -> lease_id for every task granted via
        # NODE_LEASE_BATCH and not yet completed/reclaimed/lost; the
        # membership test is what suppresses per-task dispatch events.
        self._lease_of: dict[str, str] = {}
        # lease_id -> {"granted", "consumed", "budget"} — grant/consume
        # accounting; a lease is pruned once fully consumed.
        self._leases: dict[str, dict] = {}
        self._lease_lock = threading.Lock()
        self._delegate_stats = {
            "lease_batches": 0, "tasks_leased": 0, "tasks_done": 0,
            "done_batches": 0, "dispatch_events_suppressed": 0,
            "revoked": 0,
        }
        # completion coalescing: plain-task TASK_DONEs park here and
        # flush as ONE NODE_TASK_DONE_BATCH (count/window thresholds;
        # any other state-bearing send flushes the buffer first so
        # worker_lost / refcount ordering is preserved)
        self._done_buf: list = []
        self._done_lock = threading.Lock()
        # counts TASK_DONE handlers in flight between their ledger pops
        # (scheduler FIFO / lease table) and their done-buffer park:
        # the rejoin report waits for 0 so a completing task can never
        # be invisible to every scan at once (it would be re-placed
        # and run twice)
        self._done_guard = 0
        self._done_cv = threading.Condition(self._done_lock)
        self._done_flusher = protocol.FlushLoop(
            self._flush_done_buf,
            lambda: _CFG.delegate_done_delay_ms,
            "rtpu-agent-done-flush")
        # r15 head HA: ring of recently SENT completion entries. A
        # batch can be TCP-delivered yet never processed by a dying
        # head, so on rejoin the tail of this ring (entries younger
        # than the outage minus RAY_TPU_HEAD_DONE_REPLAY_WINDOW_S) is
        # replayed — the head dedups against its rehydrated mirror,
        # making a head restart exactly-once instead of lossy.
        self._done_sent: _collections.deque = _collections.deque(
            maxlen=4096)
        self._head_lost_at: Optional[float] = None
        # ---- batched decref deltas (r16) ----
        # Worker DECREF/DECREF_BATCH traffic coalesces here as
        # per-object release counts and flushes as seq-numbered
        # NODE_DECREF_DELTA frames (collect-then-flush, the done-batch
        # discipline) toward a MINOR >= 7 head; the sent ring backs
        # the rejoin replay (head dedups by the per-node seq
        # watermark — the r15 done-replay rule extended to decrefs).
        self._decref_lock = threading.Lock()
        self._decref_buf: dict[str, int] = {}
        self._decref_seq = 0
        # serializes seq-assignment + SEND as one unit: the pacer
        # thread and an inline threshold flush racing could otherwise
        # emit seq N+1 before seq N, and the head's watermark dedup
        # would then drop frame N's releases permanently (done batches
        # tolerate reordering because they dedup per task id, not per
        # frame seq). Ordering: _decref_send_lock before _decref_lock,
        # never inverse.
        self._decref_send_lock = threading.Lock()
        self._decref_sent: _collections.deque = _collections.deque(
            maxlen=256)
        self._decref_stats = {
            "delta_frames": 0, "delta_entries": 0, "releases": 0,
            "forwarded": 0,
        }
        self._decref_flusher = protocol.FlushLoop(
            self._flush_decref_buf,
            lambda: _CFG.decref_delta_delay_ms,
            "rtpu-agent-decref-flush")
        # ---- direct actor call plane (r18): host side ----
        # Calls a remote caller dialed onto this node's listener,
        # forwarded to the actor's worker and awaiting its TASK_DONE;
        # the reply returns inline on the caller's connection, the
        # head never sees a frame. Worker death NACKs every pending
        # entry (redirect-to-head, started=True).
        self._direct_pending = _da.PendingDirectCalls()
        self._direct_stats = {"served": 0, "nacks": 0,
                              "served_bytes": 0}
        # ---- N10 heartbeat delta-sync ----
        self._hb_seq = 0
        self._hb_last_norm: Optional[dict] = None
        self._hb_conn = None
        # set by the NODE_HB_RESYNC handler (head-conn reader thread),
        # consumed ONLY by the heartbeat thread — a plain _hb_last_norm
        # reset could be overwritten mid-_heartbeat_payload and the
        # requested full snapshot silently lost
        self._hb_force_full = False
        self._labels = dict(labels or {})
        self._max_workers = max_workers
        self._resources = dict(resources)

        # initial dial retries briefly: agents are routinely started
        # before (or concurrently with) the head (`ray start` order
        # independence)
        dial_deadline = time.monotonic() + max(
            10.0, _CFG.agent_reconnect_window_s)
        while True:
            try:
                self.head = protocol.connect(
                    head_addr, self._handle_head_msg,
                    self._on_head_closed, name="head",
                    poller=self._poller)
                break
            except OSError:
                if time.monotonic() > dial_deadline:
                    raise
                time.sleep(0.3)
        if advertise_host is None:
            # The address peers should dial = the local address of our
            # outbound connection to the head (gethostbyname(hostname)
            # returns 127.0.1.1 on stock Debian /etc/hosts — useless to
            # a remote peer).
            advertise_host = self.head._sock.getsockname()[0]
        self.advertise_addr = (advertise_host, port)
        rep = self.head.request(
            {"type": protocol.NODE_REGISTER, "resources": resources,
             "labels": dict(labels or {}), "node_id": self.node_id,
             "advertise_addr": self.advertise_addr,
             "max_workers": max_workers}, timeout=30.0)
        assert rep.get("node_id") == self.node_id
        # r17: the epoch the head minted for this registration. The
        # head checks it connection-side (no per-frame bytes); we keep
        # it for logging and the fence handler's sanity check.
        self.incarnation = int(rep.get("incarnation") or 0)

        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rtpu-agent-accept", daemon=True)
        self._accept_thread.start()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="rtpu-agent-hb", daemon=True)
        self._hb_thread.start()
        # metrics plane (r11): refresh this agent's sampled gauges
        # (delegate ledger, pull-manager in-flight) at scrape time
        _mp.set_sampler("agent", self._sample_metrics)

    # ------------------------------------------------------ lifecycles
    def _on_head_closed(self, conn) -> None:
        # the head pulls over its control connection: reap any pull
        # sessions it abandoned before deciding what the outage means
        self._pull_server.on_conn_closed(conn)
        if self._stop.is_set():
            return
        if conn is not self.head:
            # a SUPERSEDED head connection died (fence reset / rejoin
            # already swapped in a fresh one): not an outage
            return
        if self._head_lost_at is None:
            self._head_lost_at = time.monotonic()
        window = _CFG.agent_reconnect_window_s
        if window <= 0:
            # Orphaned agent: the head is the only control plane — exit.
            sys.stderr.write("ray_tpu node_agent: head connection lost; "
                             "shutting down\n")
            self.shutdown()
            return
        with self._reconnect_lock:
            if self._reconnecting:
                return
            self._reconnecting = True
        threading.Thread(target=self._reconnect_loop, args=(window,),
                         name="rtpu-agent-reconnect", daemon=True).start()

    def _reconnect_loop(self, window: float) -> None:
        """Redial the head with backoff until it answers or the window
        expires. On success: re-register with the SAME node id plus a
        rejoin report (live actors, held objects) so a restarted head's
        rehydrated tables re-attach to this node's surviving state."""
        sys.stderr.write(f"ray_tpu node_agent {self.node_id}: head "
                         f"connection lost; reconnecting for up to "
                         f"{window:.0f}s\n")
        import random as _random
        deadline = time.monotonic() + window
        backoff = max(0.05, _CFG.reconnect_backoff_base_s)
        cap = max(backoff, _CFG.reconnect_backoff_cap_s)
        while not self._stop.is_set():
            if time.monotonic() > deadline:
                sys.stderr.write("ray_tpu node_agent: head did not come "
                                 "back; shutting down\n")
                self.shutdown()
                return
            # jittered exponential backoff (r17): a pod of agents
            # losing one head must not redial in lockstep, and the
            # doubling keeps a long outage from burning CPU on
            # connect attempts
            self._stop.wait(backoff * _random.uniform(0.5, 1.5))
            backoff = min(backoff * 2.0, cap)
            try:
                conn = protocol.connect(self.head_addr,
                                        self._handle_head_msg,
                                        self._on_head_closed, name="head",
                                        poller=self._poller)
            except OSError:
                continue
            # Swap BEFORE registering: the head may route work here the
            # instant it processes the register, and completions must go
            # out on the new connection, not the dead one.
            self.head = conn
            replay = self._replay_done_entries()
            dreplay = self._replay_decref_entries()
            try:
                rep = conn.request(
                    {"type": protocol.NODE_REGISTER,
                     "resources": self._resources,
                     "labels": self._labels, "node_id": self.node_id,
                     "advertise_addr": self.advertise_addr,
                     "max_workers": self._max_workers,
                     "rejoin": True,
                     "live_actors": self.scheduler.live_actors(),
                     "objects": self.store.held_objects(),
                     # r15: every task id this agent still owes the
                     # head (queued, running, leased, or with a
                     # completion in flight) — a restarted head
                     # re-places ONLY mirrored tasks absent from this
                     # set (they never arrived here)
                     "inflight_tasks": self._inflight_task_ids(replay)},
                    timeout=30.0)
                if rep.get("node_id") != self.node_id:
                    raise RuntimeError("rejoin refused")
                # Replay possibly-unprocessed sent completions FIRST
                # (they predate everything in the outage buffer); the
                # head dedups re-processed entries by the mirror pop.
                if replay:
                    conn.send({"type": protocol.NODE_TASK_DONE_BATCH,
                               "node_id": self.node_id, "done": replay,
                               "replayed": True})
                # replayed decref deltas keep their original seqs: a
                # restarted head's rehydrated watermark (or the live
                # head that already processed them) dedups each frame
                for f in dreplay:
                    conn.send(dict(f, replayed=True))
            except BaseException:
                try:
                    conn.close()
                except Exception:
                    pass
                continue
            # Flush buffered state messages BEFORE opening the direct-
            # send path (_reconnecting=False): a fresh DECREF overtaking
            # a buffered ADDREF would let a refcount dip to zero under a
            # live borrow.
            flush_failed = False
            flushed = 0
            while True:
                with self._reconnect_lock:
                    if not self._pending_sends:
                        self._reconnecting = False
                        relays, self._pending_relays = (
                            self._pending_relays, [])
                        break
                    batch = list(self._pending_sends)
                    self._pending_sends.clear()
                sent = 0
                try:
                    for m in batch:
                        conn.send(m)
                        sent += 1
                except protocol.ConnectionClosed:
                    # head bounced again mid-flush: keep the unsent tail
                    # (order-preserving) and redial — still reconnecting
                    tail = batch[sent:]
                    with self._reconnect_lock:
                        space = (self._pending_sends.maxlen
                                 - len(self._pending_sends))
                        overflow = len(tail) - space
                        if overflow > 0:
                            # evict the NEWEST buffered messages (they
                            # sort after the tail anyway) — loudly, like
                            # _append_pending_send
                            self._dropped_sends += overflow
                            sys.stderr.write(
                                f"ray_tpu node_agent {self.node_id}: "
                                f"head-outage buffer overflow during "
                                f"re-flush; dropped {overflow} newest "
                                f"state message(s)\n")
                            for _ in range(min(
                                    overflow,
                                    len(self._pending_sends))):
                                self._pending_sends.pop()
                        self._pending_sends.extendleft(reversed(tail))
                    flush_failed = True
                    break
                flushed += sent
            if flush_failed:
                continue
            sys.stderr.write(f"ray_tpu node_agent {self.node_id}: "
                             f"rejoined head ({len(replay)} sent "
                             f"completions replayed, {flushed} events + "
                             f"{len(relays)} requests flushed)\n")
            self._head_lost_at = None
            # marker AFTER the buffered backlog (connection FIFO): the
            # head defers its mirror reconcile until this arrives, so
            # buffered completions pop their mirror entries before any
            # resubmit decision is made
            try:
                conn.send({"type": protocol.NODE_EVENT,
                           "kind": "rejoin_drained",
                           "node_id": self.node_id})
            except protocol.ConnectionClosed:
                pass
            for wconn, msg in relays:
                if not wconn.closed:
                    self._relay_to_head(wconn, msg)
            return

    def _replay_done_entries(self) -> list:
        """Sent completion entries from just before the outage (the
        at-risk tail: delivered-but-maybe-unprocessed)."""
        window = _CFG.head_done_replay_window_s
        lost_at = self._head_lost_at
        if window <= 0 or lost_at is None:
            return []
        cutoff = lost_at - window
        with self._done_lock:
            return [e for ts, e in self._done_sent if ts >= cutoff]

    def _inflight_task_ids(self, replay: list) -> list:
        """Every task id still on this agent's books at rejoin time:
        leased/queued/running tasks, completions parked in the batch
        window, completions buffered through the outage, and the
        replay tail. The rehydrated head keeps these mirrored; the
        rest of its mirror re-places."""
        # Scan in the direction tasks MOVE (FIFO/lease ledgers ->
        # guard region -> done buffer): a task popped from the ledgers
        # before the first scan has a guard-counted handler in flight,
        # and the guard-wait below guarantees its done entry is parked
        # before the buffer snapshot — so a completing task is always
        # visible to at least one scan. (Holding _done_lock across the
        # scheduler scan instead would ABBA against dispatch, which
        # sends events — and thus flushes the done buffer — under the
        # scheduler lock.)
        ids = set(self.scheduler.known_task_ids())
        with self._lease_lock:
            ids.update(self._lease_of)
        with self._done_lock:
            deadline = time.monotonic() + 2.0
            while self._done_guard and time.monotonic() < deadline:
                self._done_cv.wait(0.1)
            ids.update(e.get("task_id") for e in self._done_buf)
        ids.update(e.get("task_id") for e in replay)
        with self._reconnect_lock:
            pending = list(self._pending_sends)
        for m in pending:
            t = m.get("type")
            if t == protocol.NODE_TASK_DONE_BATCH:
                ids.update(e.get("task_id") for e in m.get("done", ()))
            elif t == protocol.NODE_TASK_DONE:
                ids.add(m.get("task_id"))
            elif t == protocol.NODE_EVENT \
                    and m.get("kind") == "lease_reclaimed":
                # reclaimed specs ride back as an event: the head
                # re-places them from it — not lost, not resubmittable
                ids.update(s.task_id for s in m.get("specs", ()))
        ids.discard(None)
        return list(ids)

    def _buffer_relay(self, conn, msg: dict, depth: int = 0) -> bool:
        """Queue a worker request for replay after the head comes back;
        False when reconnection is off/over (caller drops the relay).
        If the reconnect already finished (the failure came from the OLD
        connection's futures), retry once on the new connection; a
        second failure buffers unconditionally — retrying again would
        recurse unboundedly against a flapping head."""
        if _CFG.agent_reconnect_window_s <= 0 or self._stop.is_set():
            return False
        with self._reconnect_lock:
            if self._reconnecting or depth >= 1:
                if len(self._pending_relays) >= 10_000:
                    return False
                self._pending_relays.append((conn, msg))
                return True
        self._relay_to_head(conn, msg, _retry_depth=depth + 1)
        return True

    def _sample_metrics(self) -> None:
        """Metrics-plane sampler: mirror the delegate-lease ledger and
        pull-manager occupancy into gauges (scrape-time only)."""
        m = _mp._metrics()
        with self._lease_lock:
            st = dict(self._delegate_stats)
            outstanding = len(self._lease_of)
        m.delegate.set_many(
            [({"counter": k}, float(v)) for k, v in st.items()]
            + [({"counter": "outstanding"}, float(outstanding))])
        with self._decref_lock:
            dst = dict(self._decref_stats,
                       buffered=len(self._decref_buf))
        m.decref_delta.set_many(
            [({"counter": k}, float(v)) for k, v in dst.items()])
        pm = self._pull_mgr.stats()
        m.pull_inflight.set(pm["inflight"])
        m.pull_inflight_bytes.set(pm["inflight_bytes"])
        m.direct_actor.set_many(
            [({"party": "agent", "counter": k}, float(v))
             for k, v in self._direct_stats.items()]
            + [({"party": "agent", "counter": "pending"},
                float(len(self._direct_pending)))])

    def shutdown(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        _mp.set_sampler("agent", None)
        self._done_flusher.stop()
        self._decref_flusher.stop()
        try:
            # graceful drain: completions still parked in the batch
            # window must reach the head, or it re-executes finished
            # tasks after declaring this node dead
            self._flush_done_buf()
        except Exception:
            pass
        try:
            # parked releases too, or they leak for the session
            self._flush_decref_buf()
        except Exception:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self.scheduler.shutdown()
        if self._poller is not None:
            self._poller.close()
        self.store.shutdown()
        from ray_tpu._private.specs import SESSION_TAG_INHERITED
        if not SESSION_TAG_INHERITED:
            # standalone agent (own session tag -> sole owner of its
            # segments on this host): reap orphans from killed workers.
            # An agent co-located with a head inherits the head's tag
            # and leaves the sweep to the head's shutdown.
            from ray_tpu._private.object_store import (
                sweep_session_segments)
            sweep_session_segments()

    def wait_forever(self) -> None:
        while not self._stop.is_set():
            time.sleep(0.2)

    # ------------------------------------------------------- heartbeat
    def _hb_normalize(self, key: str, value):
        """Comparison view of a heartbeat key for the N10 delta: strip
        fields that tick every beat without carrying information
        (worker ages, sample timestamps, and the wire counters' own
        per-heartbeat send cost), so a steady-state node's beats
        degenerate to seq + heartbeat presence instead of re-shipping
        the full worker table and ledgers each time."""
        if key == "workers":
            return [{k: v for k, v in row.items() if k != "age_s"}
                    for row in value]
        if key == "host_stats":
            return {k: v for k, v in value.items() if k != "ts"}
        if key == "wire":
            # every heartbeat send bumps tx_frames/tx_msgs by one, so
            # the raw counters ALWAYS differ beat-to-beat and the dict
            # would ride every delta forever. Subtract the beat count:
            # on an idle node both tick in lockstep and the normalized
            # view is constant (any fixed offset cancels); real task/
            # object traffic still changes it and ships the key.
            out = dict(value)
            for k in ("tx_frames", "tx_msgs"):
                if k in out:
                    out[k] -= self._hb_seq
            return out
        return value

    def _heartbeat_payload(self, last_spo: dict) -> tuple[dict, dict]:
        """(payload, serves_per_object sent) for one beat: the full
        snapshot, or — toward a MINOR >= 3 head — a seq-numbered delta
        carrying only the keys whose normalized value changed since
        the last beat (N10: heartbeats carry resource DELTAS; full
        snapshot on reconnect, or when the head reports a seq gap via
        NODE_HB_RESYNC)."""
        spo = self._pull_server.serves_per_object()
        plane = {
            **OBJECT_PLANE_STATS,
            "sessions": self._pull_server.session_count(),
            **{"pull_" + k: v
               for k, v in self._pull_mgr.stats().items()},
        }
        if spo != last_spo:
            plane["serves_per_object"] = spo
        with self._lease_lock:
            delegate = dict(self._delegate_stats,
                            outstanding=len(self._lease_of),
                            open_leases=len(self._leases))
        snap = {
            # agent-process frame counters (r7 frame engine
            # telemetry): plain int dict, rides the structural
            # node plane like the rest of the heartbeat
            "wire": dict(protocol.WIRE_STATS),
            # object-plane counters (r8): transfers, bytes,
            # dedup hits, per-object serve counts — the head
            # aggregates these in object_plane_stats
            "object_plane": plane,
            # tracing plane (r9): watermark ONLY — events move
            # via the trace_dump pull, never on heartbeats
            "trace_watermark": _tp.recorder().watermark(),
            # delegated-lease accounting (r10)
            "delegate": delegate,
            # direct actor plane host counters (r18)
            "direct": dict(self._direct_stats),
            **self.scheduler.heartbeat_snapshot(),
        }
        head = self.head
        if head is not self._hb_conn:
            # fresh connection (initial or post-reconnect): the head's
            # handle has no prior state — full snapshot, reset the base
            self._hb_last_norm = None
            self._hb_conn = head
        if not head.peer_speaks_delegate():
            return snap, spo             # pre-delta head: full beats
        norm = {k: self._hb_normalize(k, v) for k, v in snap.items()}
        self._hb_seq += 1
        if self._hb_force_full:
            self._hb_force_full = False
            last = None                 # head asked for a resync
        else:
            last = self._hb_last_norm
        self._hb_last_norm = norm
        if last is None:
            return dict(snap, hb_seq=self._hb_seq), spo
        delta = {k: snap[k] for k in snap if norm[k] != last.get(k)}
        delta["hb_seq"] = self._hb_seq
        delta["hb_delta"] = True
        return delta, spo

    def _heartbeat_loop(self) -> None:
        last_spo: dict = {}
        while not self._stop.is_set():
            # During a head outage the reconnect loop owns the socket:
            # skip the beat entirely (r17) instead of building a
            # payload and hammering the dead connection every 0.5 s —
            # the rejoin's register + outage-buffer flush is what
            # matters, and the post-swap connection check below resets
            # the delta base for a full first beat anyway.
            with self._reconnect_lock:
                reconnecting = self._reconnecting
            if reconnecting or self._fencing:
                self._stop.wait(HEARTBEAT_PERIOD_S)
                continue
            try:
                # per-object serve counts ride the heartbeat only when
                # they CHANGED (the head merges, keeping its last copy):
                # a steady-state cluster must not pay for a 128-entry
                # debug table twice a second per node
                payload, spo = self._heartbeat_payload(last_spo)
                self.head.send({
                    "type": protocol.NODE_HEARTBEAT,
                    "node_id": self.node_id,
                    **payload,
                })
                last_spo = spo          # only after a successful send
            except protocol.ConnectionClosed:
                # head outage: keep the thread alive — self.head is
                # swapped for a fresh connection on successful rejoin
                pass
            except Exception:
                # never let a transient snapshot/serialize error kill the
                # heartbeat thread — a silent exit here reads as node
                # death at the head
                log.exception("heartbeat send failed; retrying")
            self._stop.wait(HEARTBEAT_PERIOD_S)

    def _send_to_head(self, msg: dict, _flush_done: bool = True) -> None:
        """Fire-and-forget send that buffers during a head outage (the
        reconnect flush replays it) instead of dropping state. The
        reconnecting check comes BEFORE the direct send: once the new
        connection is live but the buffer has not drained, a direct send
        would overtake buffered messages (a fresh DECREF beating a
        buffered ADDREF lets a refcount dip to zero under a live
        borrow). Any state-bearing send drains the parked completion
        batch FIRST (same rule as the wire coalescer's eager-send
        drain): a worker_lost event must never overtake the done
        entries of tasks that worker already finished — the head would
        resubmit finished work."""
        if _flush_done and self._done_buf:
            self._flush_done_buf()
        for _attempt in range(2):
            if _CFG.agent_reconnect_window_s > 0:
                with self._reconnect_lock:
                    if self._reconnecting:
                        self._append_pending_send(msg)
                        return
            try:
                self.head.send(msg)
                return
            except protocol.ConnectionClosed:
                if (_CFG.agent_reconnect_window_s <= 0
                        or self._stop.is_set()):
                    return
                # loop: either the outage was just detected (branch
                # above buffers next pass) or the reconnect finished
                # between our read of self.head and the failed send —
                # retry once on the fresh connection
        with self._reconnect_lock:
            self._append_pending_send(msg)

    def _append_pending_send(self, msg: dict) -> None:
        """Append under _reconnect_lock; a full buffer evicts the
        OLDEST message — make that loss loud, it can strand a caller."""
        if len(self._pending_sends) == self._pending_sends.maxlen:
            self._dropped_sends += 1
            if self._dropped_sends == 1 or self._dropped_sends % 1000 == 0:
                sys.stderr.write(
                    f"ray_tpu node_agent {self.node_id}: head-outage "
                    f"buffer full; dropped {self._dropped_sends} oldest "
                    f"state message(s) — task completions/refcounts may "
                    f"be lost\n")
        self._pending_sends.append(msg)

    def send_event(self, kind: str, **fields) -> None:
        self._send_to_head({"type": protocol.NODE_EVENT, "kind": kind,
                            "node_id": self.node_id, **fields})

    # ----------------------------------------------- head-sent messages
    def _handle_head_msg(self, conn: protocol.Connection,
                         msg: dict) -> None:
        mtype = msg["type"]
        if mtype == protocol.NODE_ENQUEUE:
            self.scheduler.enqueue(msg["spec"])
        elif mtype == protocol.NODE_LEASE_BATCH:
            self._on_lease_batch(msg)
        elif mtype == protocol.NODE_LEASE_REVOKE:
            self._on_lease_revoke(conn, msg)
        elif mtype == protocol.NODE_FIND_TASK:
            hit = self.scheduler.find_task(msg["task_id"])
            conn.reply(msg, state=hit[0] if hit else None,
                       worker_id=hit[1] if hit else None)
        elif mtype == protocol.NODE_HB_RESYNC:
            # head saw a heartbeat seq gap: next beat ships the full
            # snapshot (flag, not a base reset: the heartbeat thread
            # may be mid-payload and would overwrite a cleared base)
            self._hb_force_full = True
        elif mtype == protocol.NODE_CANCEL_PENDING:
            spec = self.scheduler.cancel_pending(msg["task_id"])
            if spec is not None:
                self._lease_done(spec.task_id)
            conn.reply(msg, found=spec is not None)
        elif mtype == protocol.NODE_CANCEL_RUNNING:
            self.scheduler.cancel_running(msg["worker_id"], msg["task_id"])
        elif mtype == protocol.NODE_KILL_WORKER:
            self.scheduler.kill_worker(msg["worker_id"])
        elif mtype == protocol.NODE_SEND_ACTOR_TASK:
            ok = self.scheduler.send_actor_task(msg["worker_id"],
                                                msg["spec"])
            if not ok:
                self.send_event("actor_task_undeliverable",
                                actor_id=msg["spec"].actor_id,
                                spec=msg["spec"])
        elif mtype == protocol.NODE_RESERVE_BUNDLE:
            ok = self.scheduler.reserve_bundle(
                msg["pg_id"], msg["index"], msg["resources"])
            conn.reply(msg, ok=ok)
        elif mtype == protocol.NODE_RELEASE_BUNDLE:
            self.scheduler.release_bundle(msg["pg_id"], msg["index"])
        elif mtype == protocol.NODE_DELETE_OBJECT:
            self.store.delete(msg["object_id"])
        elif mtype == protocol.PULL_OBJECT:
            self._pull_server.handle_pull(conn, msg)
        elif mtype == protocol.PULL_CHUNK:
            self._pull_server.handle_chunk(conn, msg)
        elif mtype == protocol.BCAST_PLAN:
            OBJECT_PLANE_STATS["bcast_plans"] += 1
            self._fetch_pool.submit(self._run_bcast_plan, msg)
        elif mtype == protocol.TRACE_DUMP:
            # collection fans out to this node's workers: run on a
            # dedicated thread — never on the head connection's reader
            # (it must keep reading the worker replies), and never on
            # the fetch pool (its threads block up to bcast_timeout_s
            # in object pulls — exactly when timelines get requested)
            threading.Thread(target=self._trace_dump_reply,
                             args=(conn, msg),
                             name="rtpu-agent-trace-dump",
                             daemon=True).start()
        elif mtype == protocol.METRICS_DUMP:
            # same off-loop rule as TRACE_DUMP: the fan-out to this
            # node's workers blocks on replies that arrive on the
            # shared poller thread
            threading.Thread(target=self._metrics_dump_reply,
                             args=(conn, msg),
                             name="rtpu-agent-metrics-dump",
                             daemon=True).start()
        elif mtype == protocol.NODE_FENCED:
            # off the reader thread: the reset kills workers, redials
            # the head, and blocks in a register request — none of
            # which may run on the shared poller loop
            threading.Thread(target=self._on_fenced, args=(msg,),
                             name="rtpu-agent-fenced",
                             daemon=True).start()
        elif mtype == protocol.NODE_SHUTDOWN:
            self.shutdown()
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    # ------------------------------------- incarnation fencing (r17)
    def _on_fenced(self, msg: dict) -> None:
        """The head declared this node dead while it was alive (we
        were partitioned / stalled past the death timeout) and has
        re-placed everything we owed it. Our in-flight work, parked
        completions, and buffered releases now belong to a SUPERSEDED
        incarnation — finishing or flushing any of it would double-
        count against the re-placed winners (the head would fence the
        frames anyway). Reset: kill the workers, clear every ledger,
        re-register fresh."""
        with self._reconnect_lock:
            if self._fencing or self._stop.is_set():
                return
            self._fencing = True
        sys.stderr.write(
            f"ray_tpu node_agent {self.node_id}: FENCED by head "
            f"(stale incarnation {self.incarnation}; current "
            f"{msg.get('incarnation')}) — killing workers, clearing "
            f"ledgers, re-registering fresh\n")
        try:
            self._fence_reset()
        finally:
            with self._reconnect_lock:
                self._fencing = False

    def _fence_reset(self) -> None:
        # 1. workers + local scheduling state (the dispatch loop keeps
        #    running; fresh workers spawn for post-rejoin work)
        self.scheduler.reset_for_fence()
        # 2. every agent-side ledger and replay ring: nothing from the
        #    fenced incarnation may ever be (re)sent
        with self._lease_lock:
            self._lease_of.clear()
            self._leases.clear()
        with self._done_lock:
            self._done_buf.clear()
            self._done_sent.clear()
        with self._decref_send_lock:
            with self._decref_lock:
                self._decref_buf.clear()
                self._decref_sent.clear()
                self._decref_seq = 0   # fresh register resets the
                                       # head's watermark to match
        with self._reconnect_lock:
            self._pending_sends.clear()
            self._pending_relays = []
            self._reconnecting = False
        self._head_lost_at = None
        # 3. fresh connection + FRESH (non-rejoin) registration: the
        #    old epoch's state is gone by design, so there is nothing
        #    to replay — rejoin semantics would re-attach exactly the
        #    zombie state the fence exists to discard
        old = self.head
        deadline = time.monotonic() + max(
            10.0, _CFG.agent_reconnect_window_s)
        conn = None
        while not self._stop.is_set():
            try:
                conn = protocol.connect(
                    self.head_addr, self._handle_head_msg,
                    self._on_head_closed, name="head",
                    poller=self._poller)
                break
            except OSError:
                if time.monotonic() > deadline:
                    self.shutdown()
                    return
                self._stop.wait(0.3)
        if conn is None:
            return
        self.head = conn               # swap BEFORE closing the old
        try:
            old.close()
        except Exception:
            pass
        try:
            rep = conn.request(
                {"type": protocol.NODE_REGISTER,
                 "resources": self._resources, "labels": self._labels,
                 "node_id": self.node_id,
                 "advertise_addr": self.advertise_addr,
                 "max_workers": self._max_workers}, timeout=30.0)
            if rep.get("node_id") != self.node_id:
                raise RuntimeError("re-register refused")
            self.incarnation = int(rep.get("incarnation") or 0)
        except BaseException:
            # register failed (head flapping): close the fresh conn —
            # its on_close fires the ordinary reconnect machinery,
            # which rejoins against our (now empty) state
            try:
                conn.close()
            except Exception:
                pass
            return
        # 4. re-advertise object copies that survived the fence (real
        #    bytes in our store; the death recovery purged their
        #    locations) so getters and lineage stop regenerating them
        for oid, nbytes in self.store.held_objects():
            self.send_event("object_at", object_id=oid, nbytes=nbytes,
                            addref=False)
        sys.stderr.write(
            f"ray_tpu node_agent {self.node_id}: re-registered fresh "
            f"as incarnation {self.incarnation}\n")

    # ------------------------------------------ delegated leases (r10)
    def _on_lease_batch(self, msg: dict) -> None:
        """A bulk task lease from the head: record the grant, then
        queue every spec under ONE scheduler lock round-trip. From
        here on this agent schedules the batch against its own worker
        pool; the head hears back only through the coalesced done
        batches (and worker_lost/unplaceable events)."""
        specs = msg["specs"]
        lease_id = msg.get("lease_id", "")
        with self._lease_lock:
            self._leases[lease_id] = {
                "granted": len(specs), "consumed": 0,
                "budget": dict(msg.get("budget") or {})}
            for s in specs:
                self._lease_of[s.task_id] = lease_id
            self._delegate_stats["lease_batches"] += 1
            self._delegate_stats["tasks_leased"] += len(specs)
        self.scheduler.enqueue_many(specs)

    def _lease_done(self, task_id: str) -> Optional[str]:
        """Consume a task from its lease (completion, revoke, loss);
        prunes the lease once fully consumed. Returns the lease id if
        the task was delegated."""
        with self._lease_lock:
            lease_id = self._lease_of.pop(task_id, None)
            if lease_id is None:
                return None
            led = self._leases.get(lease_id)
            if led is not None:
                led["consumed"] += 1
                if led["consumed"] >= led["granted"]:
                    self._leases.pop(lease_id, None)
            return lease_id

    def _on_lease_revoke(self, conn: protocol.Connection,
                         msg: dict) -> None:
        """Reclaim queued-not-started tasks for the head (revoke /
        steal). The scheduler pulls pending-queue entries out
        synchronously and probes worker FIFOs through the r6
        UNQUEUE_TASK tombstone machinery; anything already started
        stays here and completes through the normal done path.

        The hand-back is a fire-and-forget ``lease_reclaimed`` NODE
        EVENT through _send_to_head — NOT a request reply — so it is
        buffered across head outages and replayed on rejoin: once the
        specs leave this agent's queue, a slow or dropped reply can
        never strand them (the head re-places from the event)."""
        def _handback(specs: list) -> None:
            if not specs:
                return

            def _send() -> None:
                for s in specs:
                    self._lease_done(s.task_id)
                with self._lease_lock:
                    self._delegate_stats["revoked"] += len(specs)
                self.send_event("lease_reclaimed", specs=specs)

            # off the caller's thread: _handback fires on the head/
            # worker connection reader (with the r10 poller, THE loop
            # thread), and send_event is a blocking head send — a
            # backpressured head must stall this hand-back, never the
            # agent's entire read loop
            threading.Thread(target=_send, name="rtpu-agent-reclaim",
                             daemon=True).start()

        self.scheduler.reclaim_tasks(list(msg.get("task_ids", ())),
                                     _handback)

    # --------------------------------- coalesced completions (r10)
    def _delegates_to_head(self) -> bool:
        return bool(_CFG.delegate) and self.head.peer_speaks_delegate()

    def _park_done(self, entry: dict) -> None:
        """Queue one plain-task completion for the next
        NODE_TASK_DONE_BATCH (collect-then-flush via the shared
        FlushLoop pacer: first entry opens a delegate_done_delay_ms
        window, delegate_done_batch entries flush inline)."""
        with self._done_lock:
            self._done_buf.append(entry)
            n = len(self._done_buf)
        if n >= max(1, _CFG.delegate_done_batch):
            self._flush_done_buf()
        else:
            self._done_flusher.wake()

    def _flush_done_buf(self) -> None:
        with self._done_lock:
            if not self._done_buf:
                return
            batch, self._done_buf = self._done_buf, []
            self._delegate_stats["done_batches"] += 1
            # retain what we are about to SEND (r15): the rejoin replay
            # re-ships the pre-outage tail of this ring, covering the
            # delivered-but-never-processed window of a dying head
            now = time.monotonic()
            self._done_sent.extend((now, e) for e in batch)
        self._send_to_head({"type": protocol.NODE_TASK_DONE_BATCH,
                            "node_id": self.node_id, "done": batch},
                           _flush_done=False)

    # ------------------------------- batched decref deltas (r16)
    def _delta_decrefs_to_head(self) -> bool:
        return (bool(_CFG.decref_delta)
                and self.head.peer_speaks_decref_delta())

    def _on_worker_decref(self, msg: dict) -> None:
        """A worker released references: coalesce into the per-object
        delta buffer (one NODE_DECREF_DELTA frame per flush window
        instead of forwarding every DECREF_BATCH), falling back to
        plain forwarding toward a pre-MINOR-7 head or with
        RAY_TPU_DECREF_DELTA=0."""
        if not self._delta_decrefs_to_head():
            self._decref_stats["forwarded"] += 1
            self._send_to_head(dict(msg))
            return
        ids = (msg.get("object_ids") if msg["type"]
               == protocol.DECREF_BATCH else [msg["object_id"]])
        with self._decref_lock:
            buf = self._decref_buf
            for oid in ids:
                buf[oid] = buf.get(oid, 0) + 1
            self._decref_stats["releases"] += len(ids)
            n = len(buf)
        if n >= max(1, _CFG.decref_delta_max):
            self._flush_decref_buf()
        else:
            self._decref_flusher.wake()

    def _flush_decref_buf(self) -> None:
        """Drain the delta buffer as one-or-more NODE_DECREF_DELTA
        frames (<= 64 entries each — the wire's structural dict
        bound). Frames are seq-numbered under the buffer lock and
        retained in the sent ring for the rejoin replay; a head
        outage parks them in the ordinary outage buffer, so ordering
        and replay both ride the existing machinery."""
        with self._decref_send_lock:
            while True:
                with self._decref_lock:
                    if not self._decref_buf:
                        return
                    buf = self._decref_buf
                    if len(buf) <= 64:
                        counts, self._decref_buf = buf, {}
                    else:
                        counts = {}
                        for oid in list(buf)[:64]:
                            counts[oid] = buf.pop(oid)
                    self._decref_seq += 1
                    frame = {"type": protocol.NODE_DECREF_DELTA,
                             "node_id": self.node_id,
                             "seq": self._decref_seq, "counts": counts}
                    self._decref_stats["delta_frames"] += 1
                    self._decref_stats["delta_entries"] += len(counts)
                    self._decref_sent.append((time.monotonic(), frame))
                # still under the SEND lock: frames leave in seq order
                self._send_to_head(frame, _flush_done=False)

    def _replay_decref_entries(self) -> list:
        """Sent delta frames from just before the outage (the at-risk
        delivered-but-maybe-unprocessed tail, the done-entry replay
        rule): the head drops any frame at or below its per-node seq
        watermark, so over-replaying is free."""
        window = _CFG.head_done_replay_window_s
        lost_at = self._head_lost_at
        if window <= 0 or lost_at is None:
            return []
        cutoff = lost_at - window
        with self._decref_lock:
            return [f for ts, f in self._decref_sent if ts >= cutoff]

    def _trace_dump_reply(self, conn: protocol.Connection,
                          msg: dict) -> None:
        """Drain this node's recorders: the agent's own first (the
        head keys its clock alignment off it), then each local
        worker's, with worker clock offsets relative to THIS agent
        (the head adds its agent offset transitively)."""
        procs = [dict(_tp.dump(), offset_ns=0, node_id=self.node_id)]
        # parallel fan-out under one shared deadline inside the
        # head's collection budget (carried on the message; a margin
        # is reserved for the reply hop): a few wedged workers must
        # not push this node past the head's deadline and drop the
        # whole node (incl. healthy workers) from the dump
        budget = max(0.5, float(msg.get("timeout", 3.0)) - 1.0)
        for wid, t0, t1, rep in _tp.fanout_dumps(
                list(self.scheduler.worker_conns()), budget):
            d = rep.get("dump")
            if d:
                procs.append(dict(
                    d, node_id=self.node_id,
                    offset_ns=_tp.rtt_offset(t0, t1, d["now_ns"])))
        try:
            # fresh clock sample AFTER the worker drain: the head
            # derives this node's offset from it, and an entry-time
            # sample would be stale by however long the drain took
            conn.reply(msg, processes=procs, now_ns=_tp.now())
        except protocol.ConnectionClosed:
            pass

    def _metrics_dump_reply(self, conn: protocol.Connection,
                            msg: dict) -> None:
        """Drain this node's metrics registries: the agent's own plus
        each local worker's, under a budget inside the head's
        collection deadline (a wedged worker must not drop the whole
        node from the scrape)."""
        procs = [dict(_mp.local_dump(), worker="")]
        budget = max(0.5, float(msg.get("timeout", 3.0)) - 1.0)
        for wid, t0, t1, rep in _tp.fanout_dumps(
                list(self.scheduler.worker_conns()), budget,
                mtype=protocol.METRICS_DUMP):
            d = rep.get("dump")
            if d and d.get("metrics"):
                procs.append(dict(d, worker=wid))
        try:
            conn.reply(msg, processes=procs)
        except protocol.ConnectionClosed:
            pass

    def _run_bcast_plan(self, msg: dict) -> None:
        """Tree-broadcast leg: pull the object from the parent the head
        named (falling back to any directory holder), store it, and
        register — which unlocks this node's own subtree head-side."""
        oid = msg["object_id"]
        if self.store.contains(oid):
            # already hold a copy through another path: (re)register so
            # the coordinator sees this node complete
            self.send_event("object_at", object_id=oid,
                            nbytes=msg.get("nbytes", 0), addref=False)
            return
        # each tree hop is one span parented under the coordinator's
        # broadcast span (envelope-carried), so the cascade's depth
        # and stalls read straight off the timeline
        with _tp.span("bcast", "hop:" + oid[:12],
                      ctx=msg.get("_trace")):
            self._pull_mgr.pull(oid, prefer=msg.get("source"),
                                timeout=_CFG.bcast_timeout_s)

    # ------------------------------------------------ local connections
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock, self._handle_local_msg,
                                       self._on_local_closed,
                                       name="agent-local", server=True,
                                       poller=self._poller)
            conn.start()

    def _on_local_closed(self, conn: protocol.Connection) -> None:
        # peer/head pullers dial the local listener: reap the pull
        # sessions a dying puller left open (blob + object pin)
        self._pull_server.on_conn_closed(conn)
        wid = conn.meta.get("worker_id")
        if wid is None or self._stop.is_set():
            return
        tasks, actor_id = self.scheduler.on_worker_lost(wid)
        # r18: every direct call pending on the dead worker NACKs
        # redirect-to-head with started=True (ambiguous — the head's
        # retry budget decides requeue vs ActorDiedError)
        for _tid, dconn, rid in \
                self._direct_pending.pop_worker(wid):
            self._direct_stats["nacks"] += 1
            _da.nack(dconn, rid, "worker_died", True)
        if tasks:
            # the dead worker may have sealed result shm on THIS host
            # without delivering TASK_DONE — reap locally (the head's
            # reap only covers its own /dev/shm)
            from ray_tpu._private.object_store import reap_object_segments
            for task in tasks:
                for oid in task.return_ids:
                    reap_object_segments(oid)
                # lease bookkeeping: the head will recover these via
                # the worker_lost event; they are off this agent's book
                self._lease_done(task.task_id)
        # send_event drains the parked done batch first (ordering:
        # completions the dead worker DID deliver must reach the head
        # before the loss event, or they'd be resubmitted)
        self.send_event("worker_lost", worker_id=wid, tasks=tasks,
                        actor_id=actor_id)

    def _handle_local_msg(self, conn: protocol.Connection,
                          msg: dict) -> None:
        """Messages from this host's workers (and peer pullers)."""
        mtype = msg["type"]
        if mtype == protocol.REGISTER:
            self.scheduler.on_worker_registered(msg["worker_id"], conn)
            # surfaced via workers_snapshot rows in heartbeats
            conn.meta["wire_native"] = bool(
                msg.get("wire_native", False))
            # r18 worker-direct serving port — rides the heartbeat's
            # worker rows so the head can resolve this worker as an
            # actor endpoint
            conn.meta["direct_port"] = msg.get("direct_port")
        elif mtype == protocol.TASK_DONE:
            self._on_task_done(conn, msg)
        elif mtype == protocol.GET_OBJECT:
            self._on_get_object(conn, msg)
        elif mtype == protocol.PUT_OBJECT:
            stored: StoredObject = msg["stored"]
            self.store.put_stored(stored)
            self.send_event("object_at", object_id=stored.object_id,
                            nbytes=stored.nbytes, addref=True,
                            contained=list(stored.contained_ids))
            conn.reply(msg, ok=True,
                       pressure=self.store.over_capacity())
        elif mtype == protocol.PULL_OBJECT:
            self._pull_server.handle_pull(conn, msg)
        elif mtype == protocol.PULL_CHUNK:
            self._pull_server.handle_chunk(conn, msg)
        elif mtype == protocol.ACTOR_TASK_DIRECT:
            self._on_actor_task_direct(conn, msg)
        elif mtype == protocol.ACTOR_INFLIGHT_DELTA:
            # a local caller's coalesced direct-call mirror: straight
            # through to the head (the add entries carry pins — they
            # must not wait out another batching window here)
            self._send_to_head(dict(msg))
        elif mtype in (protocol.WAIT, protocol.SUBMIT,
                       protocol.SUBMIT_ACTOR, protocol.SUBMIT_ACTOR_TASK,
                       protocol.KV_OP, protocol.STATE_OP,
                       protocol.ACTOR_RESOLVE):
            self._relay_to_head(conn, msg)
        elif mtype == protocol.ADDREF:
            # addrefs go straight through: delaying a release is
            # always safe (the delta buffer), delaying a borrow
            # registration is not
            self._send_to_head(dict(msg))
        elif mtype in (protocol.DECREF, protocol.DECREF_BATCH):
            self._on_worker_decref(msg)
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    def _relay_to_head(self, conn: protocol.Connection, msg: dict,
                       _retry_depth: int = 0) -> None:
        """Forward a request to the head; pipe the reply back. The
        worker's rid is restored on the way back (the head sees our
        fresh rid)."""
        worker_rid = msg.get("rid")
        is_wait = msg["type"] == protocol.WAIT
        wid = conn.meta.get("worker_id") if is_wait else None
        if wid:
            # a blocked waiter releases its resources (the agent owns
            # the ledger; the head owns the parking)
            self.scheduler.worker_blocked(wid)
        try:
            fut = self.head.request_async(dict(msg))
        except protocol.ConnectionClosed:
            if wid:
                self.scheduler.worker_unblocked(wid)
            # head outage: park the request for replay after rejoin
            # (reference raylets queue GCS RPCs across GCS restarts)
            self._buffer_relay(conn, msg, depth=_retry_depth)
            return

        def on_reply(fut) -> None:      # runs on head-conn reader thread
            try:
                rep = fut.result(timeout=0)
            except protocol.ConnectionClosed:
                if wid:
                    self.scheduler.worker_unblocked(wid)
                if not self._buffer_relay(conn, msg, depth=_retry_depth):
                    try:
                        conn.reply({"rid": worker_rid}, timeout=True)
                    except protocol.ConnectionClosed:
                        pass
                return
            except BaseException:
                rep = {}
            if wid:
                self.scheduler.worker_unblocked(wid)
            out = {k: v for k, v in rep.items()
                   if k not in ("rid", "type")}
            try:
                conn.reply({"rid": worker_rid}, **out)
            except protocol.ConnectionClosed:
                pass

        fut.add_done_callback(on_reply)

    # ------------------------------- direct actor call hosting (r18)
    def _on_actor_task_direct(self, conn: protocol.Connection,
                              msg: dict) -> None:
        """A caller dialed this node directly for an actor hosted
        here. Validate the endpoint is still current — the actor's
        worker alive and bound, this node's incarnation unchanged
        (fences callers holding a pre-fence endpoint), and the head
        reachable (a head-disconnected host may be a partitioned
        zombie whose actor the head is about to restart elsewhere:
        new calls must go back through the head) — then forward to
        the worker and remember the caller for the inline reply."""
        spec = msg["spec"]
        wid = msg.get("worker_id", "")
        with self._reconnect_lock:
            disconnected = self._reconnecting or self._fencing
        reason = None
        if (not _CFG.direct_actor or self._stop.is_set()
                or disconnected):
            reason = "host_head_disconnected"
        elif (msg.get("node_incarnation") is not None
              and msg["node_incarnation"] != self.incarnation):
            reason = "stale_incarnation"
        elif self.scheduler.worker_for_actor(
                msg.get("actor_id", "")) != wid:
            reason = "stale_endpoint"
        if reason is None:
            self._direct_pending.add(spec.task_id, conn,
                                     msg.get("rid"), wid)
            if self.scheduler.send_actor_task(wid, spec):
                self._direct_stats["served"] += 1
                return
            self._direct_pending.pop(spec.task_id)
            reason = "send_failed"
        self._direct_stats["nacks"] += 1
        _da.nack(conn, msg.get("rid"), reason, False)

    def _reply_direct_done(self, ent: tuple, msg: dict) -> None:
        """Answer a pending direct call from its worker's TASK_DONE.
        Small results ride the reply inline and the caller owns
        landing them (the driver seals into the head store in-process;
        a worker caller ships them head-ward on its coalesced delta) —
        this node keeps nothing. Large results seal HERE and the
        reply's `located` entries are the directory hints the caller
        registers with the head, so the existing pull path serves any
        getter."""
        conn, rid, _wid = ent
        inline, located = [], []
        for stored in msg.get("results", ()):
            if (stored.nbytes <= _CFG.remote_inline_max_bytes
                    or stored.is_error):
                m = materialize(stored)
                inline.append(m)
                self._direct_stats["served_bytes"] += m.nbytes
                for name in stored.shm_names:
                    unlink_segment(name)
            else:
                self.store.put_stored(stored)
                located.append((stored.object_id, stored.nbytes,
                                self.node_id,
                                list(stored.contained_ids)))
        try:
            conn.reply({"rid": rid}, inline=inline, located=located,
                       error=bool(msg.get("error")),
                       error_repr=msg.get("error_repr"))
        except protocol.ConnectionClosed:
            # caller died mid-call: its delta can never land these
            # results head-ward — seal the materialized copies locally
            # and register locations so a third-party holder of the
            # return ref still resolves (head-routed parity)
            for m in inline:
                self.store.put_stored(m)
                self.send_event("object_at", object_id=m.object_id,
                                nbytes=m.nbytes, addref=False,
                                contained=list(m.contained_ids))

    # -------------------------------------------------- task completion
    def _on_task_done(self, conn: protocol.Connection, msg: dict) -> None:
        with self._done_lock:
            self._done_guard += 1
        try:
            self._on_task_done_inner(conn, msg)
        finally:
            with self._done_lock:
                self._done_guard -= 1
                self._done_cv.notify_all()

    def _on_task_done_inner(self, conn: protocol.Connection,
                            msg: dict) -> None:
        worker_id = conn.meta.get("worker_id", "")
        if msg.get("is_actor_task"):
            if msg.get("direct_located"):
                # r18 worker-direct large results: the worker already
                # answered its caller inline; these byte carriers just
                # need the node store + a directory hint — no done
                # routing, no scheduler bookkeeping
                for stored in msg.get("results", ()):
                    self.store.put_stored(stored)
                    self.send_event(
                        "object_at", object_id=stored.object_id,
                        nbytes=stored.nbytes, addref=False,
                        contained=list(stored.contained_ids))
                return
            # r18 direct plane: this completion belongs to a caller
            # dialed onto our listener — answer it inline on that
            # connection; the head hears nothing (the caller's
            # coalesced delta is its mirror).
            ent = self._direct_pending.pop(msg.get("task_id") or "")
            if ent is not None:
                self._reply_direct_done(ent, msg)
                return
        results: list[StoredObject] = msg.get("results", [])
        inline: list[StoredObject] = []
        located: list[tuple[str, int]] = []
        for stored in results:
            if stored.nbytes <= _CFG.remote_inline_max_bytes \
                    or stored.is_error:
                inline.append(materialize(stored))
                # inline copies are head-owned; drop local segments
                for name in stored.shm_names:
                    unlink_segment(name)
            else:
                self.store.put_stored(stored)
                located.append((stored.object_id, stored.nbytes,
                                list(stored.contained_ids)))
        # release the ledger before telling the head (the head may
        # immediately route the next task here)
        is_plain = not (msg.get("is_actor_create")
                        or msg.get("is_actor_task"))
        fin_spec = None
        if msg.get("is_actor_create"):
            self.scheduler.actor_ready(worker_id)
        elif msg.get("is_actor_task"):
            pass                       # actor keeps its resources
        else:
            fin_spec = self.scheduler.task_finished(
                worker_id, msg.get("task_id"))
        ctrl = {k: v for k, v in msg.items()
                if k not in ("results", "rid", "type")}
        entry = {"worker_id": worker_id, "inline": inline,
                 "located": located, **ctrl}
        if fin_spec is not None:
            # r17: echo the attempt this node executed — the head
            # drops terminal entries whose attempt trails the live
            # spec (first-terminal-wins across re-placements)
            entry["attempt"] = int(getattr(fin_spec, "attempt", 0))
        # consume the lease UNCONDITIONALLY for plain tasks — even
        # when the batch path below is momentarily off (e.g. a fresh
        # head reconnect whose wire version is still unobserved), the
        # ledger entry must not outlive the task
        delegated = (self._lease_done(msg.get("task_id", ""))
                     if is_plain else None)
        if delegated is not None and self._delegates_to_head():
            with self._lease_lock:
                self._delegate_stats["tasks_done"] += 1
            self._park_done(entry)     # rides the next done batch
            return
        self._send_to_head({"type": protocol.NODE_TASK_DONE,
                            "node_id": self.node_id, **entry})

    # ------------------------------------------------------ object gets
    def _on_get_object(self, conn: protocol.Connection, msg: dict) -> None:
        oid = msg["object_id"]
        stored = self.store.get_stored(oid, timeout=0, restore=False)
        if stored is not None:
            conn.reply(msg, stored=stored)
            return
        wid = conn.meta.get("worker_id")
        if wid:
            self.scheduler.worker_blocked(wid)
        self._fetch_pool.submit(self._fetch_and_reply, conn, msg, oid, wid)

    def _fetch_and_reply(self, conn, msg, oid: str,
                         wid: Optional[str]) -> None:
        try:
            stored = self._fetch(oid, msg.get("timeout"),
                                 trace=msg.get("_trace"))
            if stored is not None:
                conn.reply(msg, stored=stored)
            else:
                conn.reply(msg, stored=None, timeout=True)
        except protocol.ConnectionClosed:
            pass
        finally:
            if wid:
                self.scheduler.worker_unblocked(wid)

    def _fetch(self, oid: str, timeout: Optional[float],
               trace: Optional[tuple] = None) -> Optional[StoredObject]:
        """Local store (incl. spill restore), else head lookup, else
        pull-manager transfer from any holder. The head lookup BLOCKS
        head-side until the object exists somewhere or the timeout
        passes — the agent never polls; the actual transfer dedups,
        bounds, and multi-sources through the pull manager."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            stored = self.store.get_stored(oid, timeout=0)
            if stored is not None:
                return stored
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                rep = self.head.request(
                    {"type": protocol.OBJECT_LOOKUP, "object_id": oid,
                     "timeout": remaining},
                    timeout=None if remaining is None else remaining + 10)
            except (protocol.ConnectionClosed, TimeoutError):
                return None
            if rep.get("stored") is not None:
                return rep["stored"]
            if rep.get("head_pull"):
                prefer = {"head": True}
            else:
                loc = rep.get("location")
                if loc is None:
                    return None          # head-side timeout
                prefer = (loc if loc.get("node_id") != self.node_id
                          else None)
            stored = self._pull_mgr.pull(oid, prefer=prefer,
                                         timeout=remaining,
                                         trace_ctx=trace)
            if stored is not None:
                return stored
            # every source failed (holders died / evicted, or the only
            # registered copy is our own deleted-in-flight one): the
            # stale locations were dropped via on_source_failed —
            # re-enter the lookup until our deadline so lineage
            # resubmission has time to regenerate the object
            if deadline is not None and time.monotonic() > deadline:
                return None
            time.sleep(0.1)

    # ---------------------------------------------- pull-manager hooks
    def _pull_sources(self, oid: str, prefer):
        """Source iterator for the pull manager: the preferred source
        first (broadcast parent / lookup hint), then every holder the
        directory reports (shuffled for load spread), then the head
        itself. Peer connections are dialed lazily per yield."""
        import random
        seen: set = set()
        my_addr = tuple(self.advertise_addr)

        def peer(loc):
            addr = (loc["host"], int(loc["port"]))
            if addr == my_addr:
                return None
            return self._peer_conn(addr)

        if prefer:
            if prefer.get("head"):
                seen.add("head")
                yield ("head", self.head)
            elif prefer.get("host") is not None:
                conn = peer(prefer)
                if conn is not None:
                    seen.add(prefer.get("node_id"))
                    yield (prefer.get("node_id") or
                           f"{prefer['host']}:{prefer['port']}", conn)
        try:
            rep = self.head.request(
                {"type": protocol.LOCATE_OBJECT, "object_id": oid},
                timeout=10.0)
        except (protocol.ConnectionClosed, TimeoutError):
            rep = {}
        locs = list(rep.get("locations") or ())
        random.shuffle(locs)
        # r17: suspect holders last (stable sort keeps the shuffle's
        # load spread within each group) — a gray-failing node must
        # not be the source a transfer gambles its deadline on
        locs.sort(key=lambda l: bool(l.get("suspect")))
        for loc in locs:
            nid = loc.get("node_id")
            if nid == self.node_id or nid in seen:
                continue
            conn = peer(loc)
            if conn is not None:
                seen.add(nid)
                yield (nid, conn)
        if rep.get("head_has") and "head" not in seen:
            yield ("head", self.head)

    def _on_pull_complete(self, oid: str, stored, source_id) -> None:
        """Replica registration: future readers may pull from us, the
        head's delete fan-out will reach this copy, and an active
        broadcast unlocks our subtree."""
        self._send_to_head({"type": protocol.OBJECT_ADDED,
                            "object_id": oid, "node_id": self.node_id,
                            "nbytes": stored.nbytes, "addref": False})

    def _on_pull_source_failed(self, oid: str, source_id) -> None:
        """Holder lost it (died / evicted): tell the directory so the
        stale location stops being handed out."""
        if source_id and source_id != "head":
            self._send_to_head({"type": protocol.OBJECT_REMOVED,
                                "object_id": oid,
                                "node_id": source_id})

    def _on_pull_partial(self, oid: str, nbytes: int) -> None:
        """Cut-through (r12): first chunk of a winning pull landed —
        register this node as a PARTIAL holder so the broadcast
        coordinator dispatches our subtree against the in-flight
        landing. Gated on the head demonstrating wire MINOR >= 5: an
        old head would record the partial entry as a FULL location and
        hand a half-landed copy to getters. Fire-and-forget WITHOUT
        the outage replay buffer — a partial add replayed after a head
        outage would be stale advisory state."""
        head = self.head
        if head is None or not head.peer_speaks_manifest():
            return
        try:
            head.send({"type": protocol.OBJECT_ADDED, "object_id": oid,
                       "node_id": self.node_id, "nbytes": nbytes,
                       "addref": False, "partial": True})
        except protocol.ConnectionClosed:
            pass

    def _on_pull_partial_failed(self, oid: str) -> None:
        """The transfer died after registering partial: retract the
        advisory location (children re-root via the directory)."""
        head = self.head
        if head is None or not head.peer_speaks_manifest():
            return
        try:
            head.send({"type": protocol.OBJECT_REMOVED, "object_id": oid,
                       "node_id": self.node_id})
        except protocol.ConnectionClosed:
            pass

    def _peer_conn(self, addr) -> Optional[protocol.Connection]:
        with self._peer_lock:
            conn = self._peers.get(addr)
            if conn is not None and not conn.closed:
                return conn
        try:
            conn = protocol.connect(tuple(addr), lambda c, m: None,
                                    name=f"peer-{addr[0]}:{addr[1]}",
                                    poller=self._poller)
        except OSError:
            return None
        with self._peer_lock:
            # two fetch threads may have dialed concurrently: keep the
            # winner already in the cache, close the loser
            existing = self._peers.get(tuple(addr))
            if existing is not None and not existing.closed:
                try:
                    conn.close()
                except Exception:
                    pass
                return existing
            self._peers[tuple(addr)] = conn
        return conn


def main(argv: Optional[list[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="ray_tpu node agent")
    p.add_argument("--head", required=True,
                   help="head address HOST:PORT (from ray_tpu.init on "
                        "the driver host)")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", type=str, default=None,
                   help="extra resources as JSON, e.g. '{\"accel\": 4}'")
    p.add_argument("--labels", type=str, default=None)
    p.add_argument("--max-workers", type=int, default=None)
    p.add_argument("--bind", type=str, default="0.0.0.0")
    p.add_argument("--advertise", type=str, default=None,
                   help="host peers should dial for object pulls "
                        "(default: autodetect; loopback when the head "
                        "is loopback)")
    p.add_argument("--node-id", type=str, default=None,
                   help="explicit node id (tests; default: generated)")
    args = p.parse_args(argv)

    host, port = args.head.rsplit(":", 1)
    from ray_tpu._private.runtime import detect_num_tpu_chips
    num_cpus = (args.num_cpus if args.num_cpus is not None
                else float(max(os.cpu_count() or 1, 4)))
    num_tpus = (args.num_tpus if args.num_tpus is not None
                else float(detect_num_tpu_chips()))
    resources = {"CPU": float(num_cpus)}
    if num_tpus:
        resources["TPU"] = float(num_tpus)
    resources["memory"] = float(_CFG.node_memory_bytes)
    if args.resources:
        resources.update({k: float(v)
                          for k, v in json.loads(args.resources).items()})
    agent = NodeAgent(
        (host, int(port)), resources,
        labels=json.loads(args.labels) if args.labels else None,
        max_workers=args.max_workers, bind_host=args.bind,
        advertise_host=args.advertise, node_id=args.node_id)
    sys.stderr.write(f"ray_tpu node_agent {agent.node_id} joined "
                     f"{args.head} (listening on "
                     f"{agent.advertise_addr[0]}:"
                     f"{agent.advertise_addr[1]})\n")
    try:
        agent.wait_forever()
    except KeyboardInterrupt:
        agent.shutdown()


if __name__ == "__main__":
    main()
