"""Striped (sharded) head-side hot tables (r16).

At 100k in-flight tasks the head's bookkeeping dominates per-task
cost not because any single operation is slow but because every
submit/done/decref convoys through one reentrant controller lock over
three monolithic dicts (ref/pin table, live-task spec mirror, object
directory). This module gives each table N independent stripes keyed
by task/object id, each guarded by a plain ``threading.Lock`` whose
critical section touches only that stripe's dict — submits on the
driver thread, completions on the poller thread, and decref storms on
the flusher thread stop serializing against each other, and each
acquisition is a cheap non-reentrant lock instead of an RLock.

Resident state stays bounded: a ref entry whose refcount AND pin
count are both zero is evicted from its stripe (the old
``defaultdict`` kept a zero-pin entry for every object ever probed),
terminal tasks pop their live-task entry eagerly (as before), and the
lineage mirror — the one table with no natural terminal event while
refs stay live — takes an explicit FIFO entry cap
(``RAY_TPU_HEAD_LINEAGE_MAX``).

Head-HA composition (the r15 WAL): mutate+log pairs no longer share
one controller-lock region with the snapshot's frontier capture, so
the invariant is restated per stripe:

- every table mutation completes (and its stripe lock is released)
  BEFORE its WAL record is appended, and
- ``snapshot_state`` captures the WAL frontier BEFORE capturing any
  striped table.

A record at seq <= frontier was therefore appended before the
frontier capture, which means its mutation's stripe critical section
began before the capture and the (later) stripe capture observes it;
a record at seq > frontier replays — and every record is
set-semantics, so a mutation that is BOTH captured and replayed
converges. Order-sensitive values (the absolute refcount/pin pairs)
additionally log from INSIDE their stripe lock so two racing decrefs
of one object can never log out of mutation order.

Contention observability: each acquisition first tries a non-blocking
acquire; a failure bumps the stripe's contention counter before
falling back to the blocking path, so ``/metrics`` can show whether
the stripes actually spread load (``ray_tpu_head_shard_*``).

``RAY_TPU_HEAD_SHARDS=0`` (or 1) reverts every table to a single
stripe — the pre-r16 one-dict-one-lock topology, minus the RLock.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional


def stripe_count() -> int:
    """Configured stripe count, rounded up to a power of two (the
    stripe index is ``hash(key) & (n - 1)``). 0/1 reverts to one
    stripe."""
    from ray_tpu._private.config import CONFIG
    n = int(CONFIG.head_shards)
    if n <= 1:
        return 1
    p = 1
    while p < n:
        p <<= 1
    return p


class _Striped:
    """Shared stripe machinery: lock array, dict array, contention
    counters. Subclasses hold entry-shape-specific logic."""

    __slots__ = ("n", "_mask", "_locks", "_maps", "contended")

    def __init__(self, n: Optional[int] = None):
        self.n = stripe_count() if n is None else max(1, int(n))
        self._mask = self.n - 1
        self._locks = [threading.Lock() for _ in range(self.n)]
        self._maps: list[dict] = [{} for _ in range(self.n)]
        # plain-int bumps (GIL-coherent enough for gauges)
        self.contended = [0] * self.n

    def _acquire(self, i: int) -> threading.Lock:
        lk = self._locks[i]
        if not lk.acquire(False):
            self.contended[i] += 1
            lk.acquire()
        return lk

    def _idx(self, key) -> int:
        return hash(key) & self._mask

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps)

    def stats(self) -> dict:
        sizes = [len(m) for m in self._maps]
        return {"stripes": self.n, "entries": sum(sizes),
                "max_stripe": max(sizes), "contended": sum(self.contended)}


class StripedMap(_Striped):
    """Striped key -> value map (live-task spec mirror, lineage).

    ``log`` hooks run while the stripe lock is HELD only where value
    ordering demands it (none of the map users do); mutate-then-log
    call sites sequence the append after the mutation instead — see
    the module docstring for why that is sufficient.

    ``max_entries`` bounds resident state with per-stripe FIFO
    eviction (dict insertion order; evicted keys are reported to the
    optional ``on_evict`` so callers can count them). 0 = unbounded.
    """

    __slots__ = ("_cap", "on_evict", "evicted")

    def __init__(self, n: Optional[int] = None, max_entries: int = 0,
                 on_evict: Optional[Callable[[str, Any], None]] = None):
        super().__init__(n)
        self._cap = max(0, int(max_entries))
        self.on_evict = on_evict
        self.evicted = 0

    def _stripe_cap(self) -> int:
        return (self._cap + self.n - 1) // self.n if self._cap else 0

    def put(self, key, value) -> None:
        i = self._idx(key)
        evicted = []
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            m[key] = value
            cap = self._stripe_cap()
            while cap and len(m) > cap:
                old = next(iter(m))
                evicted.append((old, m.pop(old)))
        finally:
            lk.release()
        if evicted:
            self.evicted += len(evicted)
            if self.on_evict is not None:
                for k, v in evicted:
                    self.on_evict(k, v)

    def get(self, key, default=None):
        i = self._idx(key)
        lk = self._acquire(i)
        try:
            return self._maps[i].get(key, default)
        finally:
            lk.release()

    def pop(self, key, default=None):
        i = self._idx(key)
        lk = self._acquire(i)
        try:
            return self._maps[i].pop(key, default)
        finally:
            lk.release()

    def __contains__(self, key) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def keys(self) -> list:
        out: list = []
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                out.extend(self._maps[i].keys())
            finally:
                lk.release()
        return out

    def snapshot(self) -> dict:
        """Merged plain-dict copy (snapshot-blob continuity: the
        restore side — possibly an older head — sees the same
        one-dict shape as before striping)."""
        out: dict = {}
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                out.update(self._maps[i])
            finally:
                lk.release()
        return out

    def restore(self, table: dict) -> None:
        maps: list[dict] = [{} for _ in range(self.n)]
        for k, v in table.items():
            maps[hash(k) & self._mask][k] = v
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                self._maps[i] = maps[i]
            finally:
                lk.release()


_MISSING = object()


class RefTable(_Striped):
    """Striped refcount+pin table: one entry ``[refcount, pins]`` per
    object id, evicted the moment both hit zero (bounded resident
    state — the monolithic version grew a permanent zero entry for
    every object a ``decref``/``unreferenced`` ever probed).

    The WAL hook (``log``) is called INSIDE the stripe lock with the
    post-mutation absolute values: two racing mutations of one object
    log in mutation order, which the set-semantics ``refs`` replay
    record requires (see module docstring).
    """

    __slots__ = ("log",)

    def __init__(self, n: Optional[int] = None,
                 log: Optional[Callable[[str, int, int], None]] = None):
        super().__init__(n)
        # log(oid, refcount, pins) — absolute values, called with the
        # stripe lock held; must never call back into the table.
        self.log = log

    def _log(self, oid: str, e) -> None:
        if self.log is not None:
            self.log(oid, e[0], e[1])

    def addref(self, oid: str, count: int = 1) -> None:
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            e = m.get(oid)
            if e is None:
                e = m[oid] = [0, 0]
            e[0] += count
            self._log(oid, e)
        finally:
            lk.release()

    def decref(self, oid: str, count: int = 1) -> bool:
        """Release `count` references; True when the object is now
        unreferenced AND unpinned (caller deletes it everywhere)."""
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            e = m.get(oid)
            if e is None:
                # decref of an untracked id (already released): keep
                # the legacy contract — report deletable iff unpinned,
                # and never create a resident entry for it
                if self.log is not None:
                    self.log(oid, 0, 0)
                return True
            e[0] = max(0, e[0] - count)
            self._log(oid, e)
            if e[0] == 0 and e[1] == 0:
                del m[oid]
                return True
            return e[0] == 0 and e[1] == 0
        finally:
            lk.release()

    def apply_deltas(self, counts: dict) -> list[str]:
        """Batched decref deltas (r16 NODE_DECREF_DELTA): apply
        ``{oid: n}`` grouped per stripe — each stripe lock is taken
        ONCE for all its oids — and return the ids now deletable."""
        by_stripe: dict[int, list] = {}
        for oid, n in counts.items():
            by_stripe.setdefault(self._idx(oid), []).append((oid, n))
        dead: list[str] = []
        for i, items in by_stripe.items():
            lk = self._acquire(i)
            try:
                m = self._maps[i]
                for oid, n in items:
                    e = m.get(oid)
                    if e is None:
                        if self.log is not None:
                            self.log(oid, 0, 0)
                        dead.append(oid)
                        continue
                    e[0] = max(0, e[0] - int(n))
                    self._log(oid, e)
                    if e[0] == 0 and e[1] == 0:
                        del m[oid]
                        dead.append(oid)
            finally:
                lk.release()
        return dead

    def pin(self, oid: str) -> None:
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            e = m.get(oid)
            if e is None:
                e = m[oid] = [0, 0]
            e[1] += 1
            self._log(oid, e)
        finally:
            lk.release()

    def unpin(self, oid: str) -> bool:
        """True when the object is now unreferenced and unpinned."""
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            e = m.get(oid)
            if e is None:
                if self.log is not None:
                    self.log(oid, 0, 0)
                return True
            e[1] = max(0, e[1] - 1)
            self._log(oid, e)
            if e[0] == 0 and e[1] == 0:
                del m[oid]
                return True
            return False
        finally:
            lk.release()

    def refcount(self, oid: str) -> int:
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            e = self._maps[i].get(oid)
            return e[0] if e is not None else 0
        finally:
            lk.release()

    def unreferenced(self, oid: str) -> bool:
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            e = self._maps[i].get(oid)
            return e is None or (e[0] == 0 and e[1] == 0)
        finally:
            lk.release()

    def pinned_ids(self) -> list[str]:
        out: list[str] = []
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                out.extend(oid for oid, e in self._maps[i].items()
                           if e[1] > 0)
            finally:
                lk.release()
        return out

    def set_absolute(self, oid: str, refcount: int, pins: int) -> None:
        """WAL-replay entry point (set semantics): install the absolute
        pair, evicting a now-zero entry."""
        i = self._idx(oid)
        lk = self._acquire(i)
        try:
            m = self._maps[i]
            if refcount <= 0 and pins <= 0:
                m.pop(oid, None)
            else:
                m[oid] = [max(0, int(refcount)), max(0, int(pins))]
        finally:
            lk.release()

    def snapshot(self) -> tuple[dict, dict]:
        """(refcounts, pins) as the two legacy one-dict tables —
        snapshot-blob continuity with pre-r16 heads."""
        refs: dict = {}
        pins: dict = {}
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                for oid, e in self._maps[i].items():
                    if e[0]:
                        refs[oid] = e[0]
                    if e[1]:
                        pins[oid] = e[1]
            finally:
                lk.release()
        return refs, pins

    def restore(self, refcounts: dict, pins: dict) -> None:
        maps: list[dict] = [{} for _ in range(self.n)]
        for oid, c in refcounts.items():
            if c > 0:
                maps[hash(oid) & self._mask][oid] = [int(c), 0]
        for oid, p in pins.items():
            if p > 0:
                e = maps[hash(oid) & self._mask].setdefault(oid, [0, 0])
                e[1] = int(p)
        for i in range(self.n):
            lk = self._acquire(i)
            try:
                self._maps[i] = maps[i]
            finally:
                lk.release()
