"""Cluster object directory: object_id -> holder node set.

The head-resident location service of the distributed object plane
(reference src/ray/object_manager/ownership_based_object_directory.cc;
here the head IS the owner of record for every object). Updated on
seal/put (NODE_TASK_DONE ``located`` entries, OBJECT_ADDED), on
pull-complete (a puller registers its replica and immediately serves
it), and on evict/holder-death (OBJECT_REMOVED, node purge). Read by:

- getters (head ``_pull_remote`` + agent multi-source pulls via
  LOCATE_OBJECT),
- the scheduler's locality hint (place a task where its argument
  bytes already live — ``locality_bytes``),
- the tree-broadcast coordinator (location-added listeners drive the
  dispatch cascade: a node's registration unlocks its subtree).

r16: internally striped by object id (striped.py discipline) — every
TASK_DONE ``located`` entry, OBJECT_ADDED, and delete used to take
ONE directory lock, serializing the poller thread against getters and
the locality scorer at 100k-object scale. Entries are already
reference-counted out (the holder-set emptying pops the id and its
nbytes), so striping adds no retention risk. Listeners fire OUTSIDE
the stripe locks (they send frames / touch other subsystem locks).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from ray_tpu._private import striped


class _DirStripe:
    """One stripe: its own lock + the three per-object tables."""

    __slots__ = ("lock", "locations", "partial", "nbytes")

    def __init__(self):
        self.lock = threading.Lock()
        # full holders: oid -> {node_id}
        self.locations: dict[str, set[str]] = {}
        # PARTIAL holders (r12 cut-through): nodes mid-pull that have
        # landed >= 1 chunk and can serve landed ranges to
        # manifest-speaking children. Advisory — never handed to
        # regular getters, never counted as a real copy. Promoted to
        # `locations` on the full OBJECT_ADDED, retracted on pull
        # failure / node death.
        self.partial: dict[str, set[str]] = {}
        self.nbytes: dict[str, int] = {}


class ObjectDirectory:
    def __init__(self):
        self.n = striped.stripe_count()
        self._mask = self.n - 1
        self._stripes = [_DirStripe() for _ in range(self.n)]
        self.contended = [0] * self.n
        self._listeners: list[Callable[[str, str, bool], None]] = []
        # counters for the object_plane_stats surface (plain-int bumps,
        # GIL-coherent enough for stats)
        self.adds = 0
        self.removes = 0
        self.partial_adds = 0

    def _stripe(self, oid: str) -> _DirStripe:
        i = hash(oid) & self._mask
        st = self._stripes[i]
        if not st.lock.acquire(False):
            self.contended[i] += 1
            st.lock.acquire()
        return st

    # ------------------------------------------------------- mutation
    def add_listener(self, fn: Callable[[str, str, bool], None]) -> None:
        """``fn(object_id, node_id, partial)`` runs after every NEW
        location registration (not on re-adds; partial=True for
        cut-through partial-holder adds), outside the stripe lock."""
        self._listeners.append(fn)

    def add(self, object_id: str, node_id: str, nbytes: int = 0,
            partial: bool = False) -> bool:
        """Register a copy; returns True (and notifies listeners) only
        when the holder set actually grew. ``partial=True`` records an
        advisory cut-through holder instead (ignored when the node
        already holds a full copy)."""
        st = self._stripe(object_id)
        try:
            full = st.locations.get(object_id)
            if partial:
                if full is not None and node_id in full:
                    return False          # full copy supersedes
                p = st.partial.setdefault(object_id, set())
                new = node_id not in p
                p.add(node_id)
                if nbytes:
                    st.nbytes[object_id] = nbytes
                if new:
                    self.partial_adds += 1
            else:
                s = st.locations.setdefault(object_id, set())
                new = node_id not in s
                s.add(node_id)
                # promotion: the full copy replaces the partial entry
                p = st.partial.get(object_id)
                if p is not None:
                    p.discard(node_id)
                    if not p:
                        st.partial.pop(object_id, None)
                if nbytes:
                    st.nbytes[object_id] = nbytes
                if new:
                    self.adds += 1
        finally:
            st.lock.release()
        if new:
            for fn in self._listeners:
                try:
                    fn(object_id, node_id, partial)
                except Exception:
                    pass
        return new

    def remove(self, object_id: str,
               node_id: Optional[str] = None) -> None:
        """Drop one holder (full AND partial), or the whole entry when
        node_id is None."""
        st = self._stripe(object_id)
        try:
            if node_id is None:
                if st.locations.pop(object_id, None) is not None:
                    self.removes += 1
                st.partial.pop(object_id, None)
                st.nbytes.pop(object_id, None)
                return
            p = st.partial.get(object_id)
            if p is not None and node_id in p:
                p.discard(node_id)
                if not p:
                    st.partial.pop(object_id, None)
            s = st.locations.get(object_id)
            if s is not None and node_id in s:
                s.discard(node_id)
                self.removes += 1
                if not s:
                    st.locations.pop(object_id, None)
                    st.partial.pop(object_id, None)
                    st.nbytes.pop(object_id, None)
        finally:
            st.lock.release()

    def purge_node(self, node_id: str) -> list[str]:
        """Drop `node_id` from every entry; returns object ids left
        with NO full copy anywhere (lineage-recovery candidates —
        partial holders don't count: a relay whose source died can
        never finish its copy). Sweeps one stripe at a time (node
        death is rare; holding no global lock keeps the hot paths
        moving during the sweep)."""
        orphaned: list[str] = []
        for st in self._stripes:
            with st.lock:
                for oid in list(st.partial):
                    p = st.partial[oid]
                    p.discard(node_id)
                    if not p:
                        st.partial.pop(oid, None)
                for oid in list(st.locations):
                    s = st.locations[oid]
                    if node_id in s:
                        s.discard(node_id)
                        self.removes += 1
                        if not s:
                            st.locations.pop(oid, None)
                            st.partial.pop(oid, None)
                            st.nbytes.pop(oid, None)
                            orphaned.append(oid)
        return orphaned

    # --------------------------------------------------------- queries
    def locations(self, object_id: str) -> list[str]:
        st = self._stripe(object_id)
        try:
            return list(st.locations.get(object_id, ()))
        finally:
            st.lock.release()

    def has(self, object_id: str) -> bool:
        st = self._stripe(object_id)
        try:
            return bool(st.locations.get(object_id))
        finally:
            st.lock.release()

    def holds(self, object_id: str, node_id: str) -> bool:
        st = self._stripe(object_id)
        try:
            return node_id in st.locations.get(object_id, ())
        finally:
            st.lock.release()

    def holds_partial(self, object_id: str, node_id: str) -> bool:
        st = self._stripe(object_id)
        try:
            return node_id in st.partial.get(object_id, ())
        finally:
            st.lock.release()

    def partial_locations(self, object_id: str) -> list[str]:
        st = self._stripe(object_id)
        try:
            return list(st.partial.get(object_id, ()))
        finally:
            st.lock.release()

    def nbytes(self, object_id: str) -> int:
        st = self._stripe(object_id)
        try:
            return st.nbytes.get(object_id, 0)
        finally:
            st.lock.release()

    def empty(self) -> bool:
        # lock-free scan; hint only (scheduler locality fast path)
        return not any(st.locations for st in self._stripes)

    def locality_bytes(self, object_ids: Iterable[str],
                       node_ids: Iterable[str]) -> dict[str, int]:
        """node_id -> total known bytes of `object_ids` resident there
        (objects with unknown size count 1 byte: presence still
        matters). Only nodes in `node_ids` are scored; nodes holding
        nothing are absent from the result. Each object reads only its
        own stripe."""
        wanted = set(node_ids)
        out: dict[str, int] = {}
        for oid in object_ids:
            st = self._stripe(oid)
            try:
                holders = st.locations.get(oid)
                if not holders:
                    continue
                size = max(st.nbytes.get(oid, 0), 1)
                for nid in holders:
                    if nid in wanted:
                        out[nid] = out.get(nid, 0) + size
            finally:
                st.lock.release()
        return out

    # ---------------------------------------------------- persistence
    def snapshot(self) -> tuple[dict, dict]:
        """(locations, nbytes) merged one-dict copies for the head
        snapshot (legacy blob keys; captured stripe by stripe)."""
        locations: dict = {}
        nbytes: dict = {}
        for st in self._stripes:
            with st.lock:
                for k, v in st.locations.items():
                    locations[k] = set(v)
                nbytes.update(st.nbytes)
        return locations, nbytes

    def restore(self, locations: dict, nbytes: dict) -> None:
        # partial holders deliberately don't survive a head restart:
        # they are advisory in-flight state (the pull either completes
        # and re-registers full, or failed while the head was down)
        shards: list[tuple[dict, dict]] = [({}, {})
                                           for _ in range(self.n)]
        for k, v in locations.items():
            shards[hash(k) & self._mask][0][k] = set(v)
        for k, v in nbytes.items():
            shards[hash(k) & self._mask][1][k] = v
        for st, (locs, nb) in zip(self._stripes, shards):
            with st.lock:
                st.locations = locs
                st.partial = {}
                st.nbytes = nb

    def stats(self) -> dict:
        objects = replicas = partial = tracked = 0
        for st in self._stripes:
            with st.lock:
                objects += len(st.locations)
                replicas += sum(len(s) for s in st.locations.values())
                partial += sum(len(s) for s in st.partial.values())
                tracked += sum(st.nbytes.values())
        return {
            "objects": objects,
            "replicas": replicas,
            "partial_replicas": partial,
            "tracked_bytes": tracked,
            "adds": self.adds,
            "removes": self.removes,
            "partial_adds": self.partial_adds,
        }

    def shard_stats(self) -> dict:
        sizes = [len(st.locations) for st in self._stripes]
        return {"stripes": self.n, "entries": sum(sizes),
                "max_stripe": max(sizes),
                "contended": sum(self.contended)}
