"""Cluster object directory: object_id -> holder node set.

The head-resident location service of the distributed object plane
(reference src/ray/object_manager/ownership_based_object_directory.cc;
here the head IS the owner of record for every object). Updated on
seal/put (NODE_TASK_DONE ``located`` entries, OBJECT_ADDED), on
pull-complete (a puller registers its replica and immediately serves
it), and on evict/holder-death (OBJECT_REMOVED, node purge). Read by:

- getters (head ``_pull_remote`` + agent multi-source pulls via
  LOCATE_OBJECT),
- the scheduler's locality hint (place a task where its argument
  bytes already live — ``locality_bytes``),
- the tree-broadcast coordinator (location-added listeners drive the
  dispatch cascade: a node's registration unlocks its subtree).

Listeners fire OUTSIDE the directory lock (they send frames / touch
other subsystem locks).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional


class ObjectDirectory:
    def __init__(self):
        self._lock = threading.Lock()
        self._locations: dict[str, set[str]] = {}
        # PARTIAL holders (r12 cut-through): nodes mid-pull that have
        # landed >= 1 chunk and can serve landed ranges to
        # manifest-speaking children. Advisory — never handed to
        # regular getters, never counted as a real copy (a node whose
        # only "holders" are partial is still orphaned: a relay whose
        # source died can never finish). Promoted to _locations on the
        # full OBJECT_ADDED, retracted on pull failure / node death.
        self._partial: dict[str, set[str]] = {}
        self._nbytes: dict[str, int] = {}
        self._listeners: list[Callable[[str, str, bool], None]] = []
        # counters for the object_plane_stats surface
        self.adds = 0
        self.removes = 0
        self.partial_adds = 0

    # ------------------------------------------------------- mutation
    def add_listener(self, fn: Callable[[str, str, bool], None]) -> None:
        """``fn(object_id, node_id, partial)`` runs after every NEW
        location registration (not on re-adds; partial=True for
        cut-through partial-holder adds), outside the directory
        lock."""
        self._listeners.append(fn)

    def add(self, object_id: str, node_id: str, nbytes: int = 0,
            partial: bool = False) -> bool:
        """Register a copy; returns True (and notifies listeners) only
        when the holder set actually grew. ``partial=True`` records an
        advisory cut-through holder instead (ignored when the node
        already holds a full copy)."""
        with self._lock:
            full = self._locations.get(object_id)
            if partial:
                if full is not None and node_id in full:
                    return False          # full copy supersedes
                p = self._partial.setdefault(object_id, set())
                new = node_id not in p
                p.add(node_id)
                if nbytes:
                    self._nbytes[object_id] = nbytes
                if new:
                    self.partial_adds += 1
            else:
                s = self._locations.setdefault(object_id, set())
                new = node_id not in s
                s.add(node_id)
                # promotion: the full copy replaces the partial entry
                p = self._partial.get(object_id)
                if p is not None:
                    p.discard(node_id)
                    if not p:
                        self._partial.pop(object_id, None)
                if nbytes:
                    self._nbytes[object_id] = nbytes
                if new:
                    self.adds += 1
        if new:
            for fn in self._listeners:
                try:
                    fn(object_id, node_id, partial)
                except Exception:
                    pass
        return new

    def remove(self, object_id: str,
               node_id: Optional[str] = None) -> None:
        """Drop one holder (full AND partial), or the whole entry when
        node_id is None."""
        with self._lock:
            if node_id is None:
                if self._locations.pop(object_id, None) is not None:
                    self.removes += 1
                self._partial.pop(object_id, None)
                self._nbytes.pop(object_id, None)
                return
            p = self._partial.get(object_id)
            if p is not None and node_id in p:
                p.discard(node_id)
                if not p:
                    self._partial.pop(object_id, None)
            s = self._locations.get(object_id)
            if s is not None and node_id in s:
                s.discard(node_id)
                self.removes += 1
                if not s:
                    self._locations.pop(object_id, None)
                    self._partial.pop(object_id, None)
                    self._nbytes.pop(object_id, None)

    def purge_node(self, node_id: str) -> list[str]:
        """Drop `node_id` from every entry; returns object ids left
        with NO full copy anywhere (lineage-recovery candidates —
        partial holders don't count: a relay whose source died can
        never finish its copy)."""
        orphaned: list[str] = []
        with self._lock:
            for oid in list(self._partial):
                p = self._partial[oid]
                p.discard(node_id)
                if not p:
                    self._partial.pop(oid, None)
            for oid in list(self._locations):
                s = self._locations[oid]
                if node_id in s:
                    s.discard(node_id)
                    self.removes += 1
                    if not s:
                        self._locations.pop(oid, None)
                        self._partial.pop(oid, None)
                        self._nbytes.pop(oid, None)
                        orphaned.append(oid)
        return orphaned

    # --------------------------------------------------------- queries
    def locations(self, object_id: str) -> list[str]:
        with self._lock:
            return list(self._locations.get(object_id, ()))

    def has(self, object_id: str) -> bool:
        with self._lock:
            return bool(self._locations.get(object_id))

    def holds(self, object_id: str, node_id: str) -> bool:
        with self._lock:
            return node_id in self._locations.get(object_id, ())

    def holds_partial(self, object_id: str, node_id: str) -> bool:
        with self._lock:
            return node_id in self._partial.get(object_id, ())

    def partial_locations(self, object_id: str) -> list[str]:
        with self._lock:
            return list(self._partial.get(object_id, ()))

    def nbytes(self, object_id: str) -> int:
        with self._lock:
            return self._nbytes.get(object_id, 0)

    def empty(self) -> bool:
        return not self._locations          # atomic read; hint only

    def locality_bytes(self, object_ids: Iterable[str],
                       node_ids: Iterable[str]) -> dict[str, int]:
        """node_id -> total known bytes of `object_ids` resident there
        (objects with unknown size count 1 byte: presence still
        matters). Only nodes in `node_ids` are scored; nodes holding
        nothing are absent from the result."""
        wanted = set(node_ids)
        out: dict[str, int] = {}
        with self._lock:
            for oid in object_ids:
                holders = self._locations.get(oid)
                if not holders:
                    continue
                size = max(self._nbytes.get(oid, 0), 1)
                for nid in holders:
                    if nid in wanted:
                        out[nid] = out.get(nid, 0) + size
        return out

    # ---------------------------------------------------- persistence
    def snapshot(self) -> tuple[dict, dict]:
        """(locations, nbytes) table copies for the head snapshot."""
        with self._lock:
            return ({k: set(v) for k, v in self._locations.items()},
                    dict(self._nbytes))

    def restore(self, locations: dict, nbytes: dict) -> None:
        # partial holders deliberately don't survive a head restart:
        # they are advisory in-flight state (the pull either completes
        # and re-registers full, or failed while the head was down)
        with self._lock:
            self._locations = {k: set(v) for k, v in locations.items()}
            self._partial = {}
            self._nbytes = dict(nbytes)

    def stats(self) -> dict:
        with self._lock:
            return {
                "objects": len(self._locations),
                "replicas": sum(len(s)
                                for s in self._locations.values()),
                "partial_replicas": sum(len(s)
                                        for s in self._partial.values()),
                "tracked_bytes": sum(self._nbytes.values()),
                "adds": self.adds,
                "removes": self.removes,
                "partial_adds": self.partial_adds,
            }
