"""Cross-host object transfer: chunked pull protocol.

The reference moves objects node-to-node with a chunked push/pull plane
(reference src/ray/object_manager/object_manager.cc, pull_manager.cc,
object_buffer_pool.cc chunking). Here the equivalent is a pull-only
protocol riding the framed-message channel:

    PULL_OBJECT {object_id[, manifest]} -> {found, pull_id, nchunks,
                                            size[, manifest]}
    PULL_CHUNK  {pull_id, index}        -> {data|raw}       (x nchunks)

Two serve/land paths coexist on the wire (negotiated per transfer by
the REPLY SHAPE — the puller asks for a manifest; a holder that
predates wire MINOR 5, or has ``RAY_TPU_PULL_MANIFEST=0``, ignores the
unknown request key and answers with the blob protocol):

**Manifest path (r12, the default).** The PULL reply describes the
object instead of copying it: payload length + per-buffer kinds/sizes.
The logical transfer stream is ``payload · buffer0 · buffer1 ...``
split at fixed CHUNK_BYTES boundaries, and chunk bodies ride the
Envelope ``raw`` field — emitted scatter-gather straight from the
holder's mapped shm (zero serve-side copies; one mapping serves every
concurrent session) and landed by the puller straight into
pre-created pooled shm segments at each chunk's offset via the native
GIL-released memcpy (one land-side copy, the unavoidable wire->memory
one; no bytearray reassembly, no ``_decode`` re-pickle, no second
copy into the store). With ``RAY_TPU_PULL_CUT_THROUGH`` (default on)
a puller also serves its ALREADY-LANDED chunk ranges to its own
children while the pull is still in flight — the broadcast tree's
cut-through relay: chunk requests for not-yet-landed ranges park on
the landing (event-driven, never blocking the shared read loop) and
answer the moment the range lands, so tree depth costs per-chunk, not
per-object, latency.

**Blob path (pre-MINOR-5 interop).** ``materialize()`` + pickle of the
whole StoredObject served in slices — byte-identical to the r8
protocol, kept so old peers interoperate in both directions.

Serving side (PullServer):
- a pull session PINS its object in the local store for its lifetime
  (`pin_local`), so the LRU spill pass cannot unlink segments
  mid-transfer; manifest sessions additionally hold their segment
  names in ``guard_segments`` so a concurrent refcount-zero delete
  unlinks (mapping-safe) instead of pooling pages out from under the
  mapped views; if the object was ALREADY spilled (or spills in the
  probe->encode window), the serve path restores from the spill file
  and retries instead of failing the segment map;
- sessions expire after `pull_session_ttl_s`: the sweep runs lazily on
  every pull/chunk message AND on the puller's connection close, so
  pullers that die mid-pull cannot leak blobs, mappings or pins;
- concurrent pulls of one object share a single source — one encoded
  blob (blob path) or one set of shm mappings (manifest path): N
  children of one tree node cost one encode/mapping.

Client side (``pull_object``): a dropped/expired chunk re-opens the
session with the holder and resumes from the failed index, up to
`pull_chunk_retries` times; a chunk-wait on a PARTIAL holder that
exceeds ``pull_partial_chunk_timeout_s`` counts as a drop, so a
stalled relay degrades to the existing retry / re-root-on-source
machinery instead of burning the transfer deadline. Transfer/serve/
retry/copy counters accumulate in ``OBJECT_PLANE_STATS`` (surfaced via
the ``object_plane_stats`` state op, node heartbeats, and the metrics
plane).
"""
from __future__ import annotations

import bisect
import pickle
import threading
import time
import uuid
import weakref
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG
from ray_tpu._private.object_store import (StoredObject, _local_tag,
                                           _map_segment,
                                           _open_segment_for_write,
                                           bulk_copy, guard_segments,
                                           unlink_segment)
from ray_tpu._private.wire import RAW_KEY

CHUNK_BYTES = 4 * 1024 * 1024

# Process-wide object-plane counters (this process's transfers only):
# plain int increments under the GIL, same discipline as
# protocol.WIRE_STATS. Agents carry a copy on heartbeats; the head
# aggregates per node in the object_plane_stats state op.
OBJECT_PLANE_STATS = {
    "pulls_started": 0,       # transfers this process initiated
    "pulls_completed": 0,
    "pulls_failed": 0,
    "pull_bytes": 0,
    "pull_dedup_hits": 0,     # pulls that joined an in-flight transfer
    "pull_suspect_deferred": 0,  # holders deferred to the rotation
                              #   tail because their node is SUSPECT
                              #   (r17 gray-failure deprioritization)
    "chunk_retries": 0,       # chunk-level session re-opens
    "serves_started": 0,      # pull sessions opened by remote pullers
    "serves_completed": 0,
    "serve_bytes": 0,
    "bcast_plans": 0,         # BCAST_PLAN messages acted on (agents)
    # ---- r12 zero-copy envelope ----
    "manifest_pulls": 0,      # transfers that ran the manifest protocol
    "blob_pulls": 0,          # transfers on the pre-MINOR-5 blob path
    "serve_bytes_copied": 0,  # user-space serve-side copies (blob only)
    "land_bytes_copied": 0,   # user-space land-side copies: manifest =
                              #   the single wire->shm memcpy; blob =
                              #   the reassembly join (a LOWER bound —
                              #   the _decode re-pickle copies again)
    "partial_serves": 0,      # chunk ranges served from an in-flight
                              #   landing (cut-through relay)
    "partial_waits": 0,       # chunk requests parked on a landing
}


def materialize(obj: StoredObject) -> StoredObject:
    """Copy of `obj` with every shm-backed buffer pulled inline — the
    blob path's transportable form (the manifest path never calls
    this; it serves straight from the mapping)."""
    if not obj.shm_names:
        return obj
    inline: list[bytes] = []
    ii = si = 0
    order: list[str] = []
    # guard: a concurrent refcount-zero free in this process must
    # unlink (mapping-safe), not pool-and-reuse, while we copy
    with guard_segments(obj.shm_names):
        for kind in obj.buffer_order:
            if kind == "i":
                inline.append(obj.inline_buffers[ii]); ii += 1
            else:
                mv = _map_segment(obj.shm_names[si], obj.shm_sizes[si])
                inline.append(mv.tobytes())
                OBJECT_PLANE_STATS["serve_bytes_copied"] += len(mv)
                del mv
                si += 1
            order.append("i")
    return StoredObject(obj.object_id, obj.payload, inline, [], [],
                        order, obj.is_error,
                        contained_ids=list(obj.contained_ids))


def _encode(obj: StoredObject) -> bytes:
    blob = pickle.dumps(materialize(obj),
                        protocol=pickle.HIGHEST_PROTOCOL)
    OBJECT_PLANE_STATS["serve_bytes_copied"] += len(blob)
    return blob


def _decode(data: bytes) -> StoredObject:
    return pickle.loads(data)


class PullBudgetExceeded(Exception):
    """The in-flight byte budget could not admit this transfer before
    the deadline — NOT a source failure (the holder is fine), so pull
    managers must not drop the location over it."""


# ====================================================================
# manifest chunk sources
# ====================================================================

def _nchunks(total: int) -> int:
    return max(1, (total + CHUNK_BYTES - 1) // CHUNK_BYTES)


class _SpanSet:
    """The manifest transfer stream — ``payload · buffer0 · ...`` — as
    gatherable buffer views with cumulative offsets."""

    def __init__(self, buffers):
        self.views = [memoryview(b) for b in buffers]
        self.offsets: list[int] = []
        off = 0
        for v in self.views:
            self.offsets.append(off)
            off += len(v)
        self.total = off

    def gather(self, start: int, end: int) -> list:
        """Zero-copy views covering stream range [start, end)."""
        out = []
        i = bisect.bisect_right(self.offsets, start) - 1
        pos = start
        while pos < end:
            off, v = self.offsets[i], self.views[i]
            a = pos - off
            b = min(len(v), end - off)
            out.append(v[a:b])
            pos = off + b
            i += 1
        return out

    def chunk_range(self, index: int) -> tuple[int, int]:
        start = index * CHUNK_BYTES
        return start, min(start + CHUNK_BYTES, self.total)


class _ChunkSource:
    """Serve-side descriptor of a COMPLETE object: the manifest plus
    mapped views of every span, shared by all concurrent sessions (one
    mapping serves N tree children). Refcounted; while alive it holds
    a store pin (spill protection) and guards its shm names (a
    refcount-zero delete unlinks instead of pooling, so the mapped
    pages survive under in-flight serves)."""

    partial = False

    def __init__(self, stored: StoredObject, store=None):
        self.object_id = stored.object_id
        self.kinds = list(stored.buffer_order)
        self.sizes: list[int] = []
        self.is_error = stored.is_error
        self.contained = list(stored.contained_ids)
        self._store = store
        self._shm_names = list(stored.shm_names)
        self._guard = guard_segments(self._shm_names)
        self._guard.__enter__()
        try:
            bufs = [stored.payload]
            ii = si = 0
            for kind in stored.buffer_order:
                if kind == "i":
                    b = stored.inline_buffers[ii]; ii += 1
                else:
                    b = _map_segment(stored.shm_names[si],
                                     stored.shm_sizes[si])
                    si += 1
                self.sizes.append(len(b))
                bufs.append(b)
            self.spans = _SpanSet(bufs)
        except BaseException:
            self._guard.__exit__(None, None, None)
            raise
        self.payload_len = len(stored.payload)
        self.total = self.spans.total
        self.nchunks = _nchunks(self.total)
        self._refs = 1
        self._lock = threading.Lock()
        self._pinned = False
        if store is not None:
            pin = getattr(store, "pin_local", None)
            if pin is not None:
                pin(self.object_id)
                self._pinned = True

    def manifest(self) -> dict:
        return {"payload": self.payload_len, "kinds": "".join(self.kinds),
                "sizes": list(self.sizes), "is_error": self.is_error,
                "contained": list(self.contained),
                "partial": self.partial}

    def ready(self, index: int) -> bool:
        return True

    def gather(self, index: int) -> list:
        return self.spans.gather(*self.spans.chunk_range(index))

    # ------------------------------------------------------ lifetime
    def acquire(self) -> bool:
        with self._lock:
            if self._refs <= 0:
                return False         # already torn down: don't revive
            self._refs += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            if self._refs > 0:
                return
        self._close()

    def _close(self) -> None:
        self._guard.__exit__(None, None, None)
        if self._pinned:
            unpin = getattr(self._store, "unpin_local", None)
            if unpin is not None:
                try:
                    unpin(self.object_id)
                except Exception:
                    pass
            self._pinned = False


class Landing:
    """Land-side state of one in-flight manifest transfer: pre-created
    pooled shm segments + inline bytearrays that chunk bodies memcpy
    into at their stream offsets, a landed bitmap, and parked chunk
    waiters (the cut-through children). Doubles as the chunk source
    for sessions serving FROM the landing: a chunk range is servable
    the moment it lands, while the node's own pull is still running.

    Waiter callbacks fire on the landing thread (the puller's transfer
    thread) — never on the shared read loop. A child's send can block
    up to the socket send budget; that throttles this node's relay,
    not any reader."""

    def __init__(self, store, object_id: str, manifest: dict,
                 size: int):
        self.object_id = object_id
        self.payload_len = int(manifest["payload"])
        self.kinds = list(manifest["kinds"])
        self.sizes = [int(s) for s in manifest["sizes"]]
        self.is_error = bool(manifest.get("is_error"))
        self.contained = list(manifest.get("contained") or ())
        self._store = store
        tag = uuid.uuid4().hex[:6]
        self.shm_names: list[str] = []
        self.shm_sizes: list[int] = []
        self.shm_alloc: list[int] = []
        self._mms: list = []
        self._inline: list[bytearray] = []
        bufs: list = []
        payload = bytearray(self.payload_len)
        bufs.append(payload)
        self._payload = payload
        try:
            for i, kind in enumerate(self.kinds):
                n = self.sizes[i]
                if kind == "s":
                    # unique name: same-host peers share /dev/shm, so
                    # the producer's rtpu_<tag>_<oid>_<i> names (and
                    # other pullers' landings) must never collide;
                    # still session-tag-prefixed for the shutdown sweep
                    name = (f"rtpu_{_local_tag()}_{object_id}"
                            f"_l{tag}_{i}")
                    mm, alloc = _open_segment_for_write(name, n)
                    self.shm_names.append(name)
                    self.shm_sizes.append(n)
                    self.shm_alloc.append(alloc)
                    self._mms.append(mm)
                    bufs.append(memoryview(mm))
                else:
                    ba = bytearray(n)
                    self._inline.append(ba)
                    bufs.append(ba)
        except BaseException:
            self._destroy_segments()
            raise
        self.spans = _SpanSet(bufs)
        self.total = self.spans.total
        if self.total != size:
            self._destroy_segments()
            raise ValueError(f"manifest total {self.total} != "
                             f"advertised size {size}")
        self.nchunks = _nchunks(self.total)
        self._landed = [False] * self.nchunks
        self.n_landed = 0
        self.failed = False
        self.done = False
        self._lock = threading.Lock()
        # index -> [(callback, deadline)]: parked cut-through serves
        self._waiters: dict[int, list] = {}
        self._refs = 1                       # owner (the pull) holds one
        self._guard = guard_segments(self.shm_names)
        self._guard.__enter__()

    partial = True

    def manifest(self) -> dict:
        return {"payload": self.payload_len, "kinds": "".join(self.kinds),
                "sizes": list(self.sizes), "is_error": self.is_error,
                "contained": list(self.contained), "partial": True}

    def matches(self, manifest: dict, size: int) -> bool:
        """Same incarnation? (retry re-opens must resume the same
        deterministic chunk grid)"""
        return (size == self.total
                and int(manifest["payload"]) == self.payload_len
                and [int(s) for s in manifest["sizes"]] == self.sizes)

    # ------------------------------------------------------- landing
    def write_chunk(self, index: int, raw) -> bool:
        """Land one chunk body at its stream offset. Returns True when
        the chunk was new (False: duplicate from a retry). Fires any
        parked waiters for the range outside the lock."""
        start, end = self.spans.chunk_range(index)
        view = memoryview(raw)
        if len(view) != end - start:
            raise ValueError(
                f"chunk {index}: got {len(view)} bytes, "
                f"want {end - start}")
        with self._lock:
            if self.failed or self._landed[index]:
                return False
        consumed = 0
        for dst in self.spans.gather(start, end):
            n = len(dst)
            bulk_copy(dst, 0, view[consumed:consumed + n])
            consumed += n
        OBJECT_PLANE_STATS["land_bytes_copied"] += end - start
        with self._lock:
            if self._landed[index]:
                return False
            self._landed[index] = True
            self.n_landed += 1
            waiters = self._waiters.pop(index, ())
        for cb, _deadline in waiters:
            try:
                cb(True)
            except Exception:
                pass
        return True

    def ready(self, index: int) -> bool:
        with self._lock:
            return self._landed[index] and not self.failed

    def gather(self, index: int) -> list:
        return self.spans.gather(*self.spans.chunk_range(index))

    def add_waiter(self, index: int, cb: Callable[[bool], None]) -> bool:
        """Park a cut-through chunk serve until the range lands; the
        callback fires with True (landed) or False (landing failed).
        Returns False when the landing can no longer answer (failed,
        or the index is out of range) — the caller replies dropped."""
        with self._lock:
            if self.failed or index >= self.nchunks:
                return False
            if self._landed[index]:
                pass                         # fire immediately below
            else:
                OBJECT_PLANE_STATS["partial_waits"] += 1
                self._waiters.setdefault(index, []).append(
                    (cb, time.monotonic()))
                return True
        try:
            cb(True)
        except Exception:
            pass
        return True

    def complete(self) -> StoredObject:
        """All chunks landed: build the StoredObject backed by the
        landed segments (no copies — payload/inline stay the landed
        bytearrays, pickle handles them like bytes)."""
        with self._lock:
            assert self.n_landed == self.nchunks
            self.done = True
        return StoredObject(
            self.object_id, self._payload, list(self._inline),
            list(self.shm_names), list(self.shm_sizes),
            list(self.kinds), self.is_error,
            contained_ids=list(self.contained),
            shm_alloc_sizes=list(self.shm_alloc))

    def fail(self) -> None:
        """The pull died: answer every parked waiter with failure so
        children fall back to their retry / re-root machinery."""
        with self._lock:
            if self.failed:
                return
            self.failed = True
            waiters, self._waiters = self._waiters, {}
        for lst in waiters.values():
            for cb, _deadline in lst:
                try:
                    cb(False)
                except Exception:
                    pass

    # ------------------------------------------------------ lifetime
    def acquire(self) -> bool:
        with self._lock:
            if self._refs <= 0:
                return False         # already torn down: don't revive
            self._refs += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            if self._refs > 0:
                return
        self._guard.__exit__(None, None, None)
        for mm in self._mms:
            try:
                mm.close()
            except (BufferError, ValueError):
                pass                 # exported views still alive: GC
        self._mms = []
        if not self.done:
            self._destroy_segments()

    def _destroy_segments(self) -> None:
        for name in self.shm_names:
            unlink_segment(name)


class _LandingTable:
    """Per-store registry of in-flight landings — the hand-off point
    between the land path (pull_object) and the serve path
    (PullServer cut-through)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._landings: dict[str, Landing] = {}

    def put(self, oid: str, landing: Landing) -> None:
        with self._lock:
            self._landings[oid] = landing

    def get(self, oid: str) -> Optional[Landing]:
        with self._lock:
            return self._landings.get(oid)

    def remove(self, oid: str, landing: Landing) -> None:
        with self._lock:
            if self._landings.get(oid) is landing:
                self._landings.pop(oid, None)


def landing_table(store) -> _LandingTable:
    """The store's landing table (lazily attached: PullServer and the
    pull managers share whatever store instance they were built on)."""
    table = getattr(store, "_rtpu_landings", None)
    if table is None:
        table = store._rtpu_landings = _LandingTable()
    return table


@dataclass
class _PullSession:
    object_id: str
    touched: float
    blob: Optional[bytes] = None         # blob protocol
    source: Optional[object] = None      # manifest: _ChunkSource/Landing
    conn_id: Optional[int] = None        # id(conn) of the puller
    pinned: bool = False


class PullServer:
    """Serves PULL_OBJECT / PULL_CHUNK against a LocalStore. Mixed into
    any endpoint that holds objects (head runtime, node agent).

    `executor` (when given) takes the slow path — spill restore from
    disk + blob encode / manifest mapping — off the connection reader
    thread, so a multi-GB restore can never stall heartbeat processing
    on a shared control connection. Cut-through serves from an
    in-flight landing stay inline (no disk IO; not-yet-landed chunks
    park event-driven instead of blocking)."""

    # bounded per-object serve-count table (object_plane_stats surface;
    # the broadcast tests assert per-node serve counts from it)
    _SERVES_PER_OBJECT_CAP = 128

    def __init__(self, store, executor=None):
        self._store = store
        self._executor = executor
        self._sessions: dict[str, _PullSession] = {}
        self._slock = threading.Lock()
        self._last_sweep = time.monotonic()
        # oid -> (weakref to the StoredObject encoded, payload, created):
        # while the store still holds that exact instance, concurrent
        # sessions share one encode/mapping (a re-put/restore swaps the
        # instance, so a stale source can never be served)
        self._blob_cache: dict[str, tuple] = {}
        self._manifest_cache: dict[str, tuple] = {}
        self._serves_per_object: dict[str, int] = {}

    # ----------------------------------------------------- sessions
    def _drop_session_locked(self, pull_id: str) -> None:
        sess = self._sessions.pop(pull_id, None)
        if sess is None:
            return
        if sess.pinned:
            self._unpin(sess.object_id)
        if sess.source is not None:
            sess.source.release()
        # last session of this object gone: release the shared source —
        # the caches exist to amortize CONCURRENT sessions (tree
        # children), not to hold multi-GB bytes/mappings on an idle node
        if not any(s.object_id == sess.object_id
                   for s in self._sessions.values()):
            self._blob_cache.pop(sess.object_id, None)
            ent = self._manifest_cache.pop(sess.object_id, None)
            if ent is not None:
                ent[1].release()

    def _unpin(self, oid: str) -> None:
        unpin = getattr(self._store, "unpin_local", None)
        if unpin is not None:
            try:
                unpin(oid)
            except Exception:
                pass

    def sweep(self, force: bool = False) -> int:
        """Lazy TTL sweep: reap sessions idle past pull_session_ttl_s.
        Runs (throttled) on every pull/chunk message so expiry does not
        depend on further traffic for the SAME session — pullers that
        die mid-pull cannot leak materialized blobs/mappings/pins."""
        now = time.monotonic()
        if not force and now - self._last_sweep < 1.0:
            return 0
        ttl = _CFG.pull_session_ttl_s
        with self._slock:
            self._last_sweep = now
            dead = [k for k, s in self._sessions.items()
                    if now - s.touched > ttl]
            for k in dead:
                self._drop_session_locked(k)
            # cache entries whose StoredObject died (deleted / re-put)
            # or that went idle are dropped with the sessions
            for oid in list(self._blob_cache):
                ref, _, created = self._blob_cache[oid]
                if ref() is None or now - created > ttl:
                    self._blob_cache.pop(oid, None)
            for oid in list(self._manifest_cache):
                ref, src, created = self._manifest_cache[oid]
                if ref() is None or now - created > ttl:
                    self._manifest_cache.pop(oid, None)
                    src.release()
        return len(dead)

    def on_conn_closed(self, conn) -> None:
        """Reap every session the closing connection's puller opened —
        the other half of dead-puller cleanup (the lazy sweep covers
        holders that never hear from anyone again)."""
        cid = id(conn)
        with self._slock:
            for k in [k for k, s in self._sessions.items()
                      if s.conn_id == cid]:
                self._drop_session_locked(k)

    def session_count(self) -> int:
        with self._slock:
            return len(self._sessions)

    def serves_per_object(self) -> dict[str, int]:
        with self._slock:
            return dict(self._serves_per_object)

    # ------------------------------------------------------- serving
    def handle_pull(self, conn: protocol.Connection, msg: dict) -> None:
        """Runs on the connection reader thread: answer only the cheap
        cases inline (not-found; cut-through landing serves — pure
        bookkeeping); ALL store serving (the mapping/_encode of a
        possibly multi-GB object, and any spill restore) goes to the
        executor so the reader thread never stalls heartbeats/control
        traffic."""
        self.sweep()
        oid = msg["object_id"]
        stored = self._store.get_stored(oid, timeout=0, restore=False)
        if stored is None and not self._store.contains(oid):
            # cut-through: a landing in flight serves its landed
            # ranges to manifest-speaking children
            if (msg.get("manifest") and _CFG.pull_manifest
                    and _CFG.pull_cut_through):
                landing = landing_table(self._store).get(oid)
                if landing is not None and not landing.failed:
                    self._open_session(conn, msg, landing,
                                       acquire=True)
                    return
            stored = self._store.get_stored(oid, timeout=0)
            if stored is None:
                conn.reply(msg, found=False)
                return
        if self._executor is not None:
            self._executor.submit(self._pull_slow, conn, msg, oid)
        elif stored is not None:
            self._serve(conn, msg, stored)
        else:
            self._pull_slow(conn, msg, oid)

    def _pull_slow(self, conn: protocol.Connection, msg: dict,
                   oid: str) -> None:
        try:
            stored = self._store.get_stored(oid, timeout=10)
            if stored is None:
                conn.reply(msg, found=False)
            else:
                self._serve(conn, msg, stored)
        except protocol.ConnectionClosed:
            pass

    def _encode_shared(self, stored) -> bytes:
        """Encode `stored`, sharing the blob across concurrent sessions
        of the same object while the store holds that exact instance
        (tree broadcast: fanout children of one node pay one encode)."""
        oid = stored.object_id
        with self._slock:
            ent = self._blob_cache.get(oid)
            if ent is not None and ent[0]() is stored:
                return ent[1]
        blob = _encode(stored)
        with self._slock:
            if len(self._blob_cache) >= 4:       # bounded: oldest out
                oldest = min(self._blob_cache,
                             key=lambda k: self._blob_cache[k][2])
                self._blob_cache.pop(oldest, None)
            self._blob_cache[oid] = (weakref.ref(stored), blob,
                                     time.monotonic())
        return blob

    def _source_shared(self, stored) -> _ChunkSource:
        """Map `stored` into a chunk source, shared across concurrent
        sessions while the store holds that exact instance — one set
        of mappings serves every tree child."""
        oid = stored.object_id
        with self._slock:
            ent = self._manifest_cache.get(oid)
            if ent is not None and ent[0]() is stored:
                ent[1].acquire()
                return ent[1]
        src = _ChunkSource(stored, store=self._store)
        src.acquire()                            # the session's ref
        with self._slock:
            old = self._manifest_cache.pop(oid, None)
            if len(self._manifest_cache) >= 4:   # bounded: oldest out
                oldest = min(self._manifest_cache,
                             key=lambda k: self._manifest_cache[k][2])
                self._manifest_cache.pop(oldest)[1].release()
            self._manifest_cache[oid] = (weakref.ref(stored), src,
                                         time.monotonic())
        if old is not None:
            old[1].release()
        return src

    def _serve(self, conn: protocol.Connection, msg: dict,
               stored) -> None:
        oid = stored.object_id
        manifest_mode = bool(msg.get("manifest")) and _CFG.pull_manifest
        # Pin for the life of the session: the spill pass must not
        # unlink this object's segments (or evict the restored copy)
        # while chunks are still being read.
        pin = getattr(self._store, "pin_local", None)
        pinned = False
        if pin is not None:
            pin(oid)
            pinned = True
        blob = source = None
        try:
            for _attempt in range(3):
                try:
                    if manifest_mode:
                        source = self._source_shared(stored)
                    else:
                        blob = self._encode_shared(stored)
                    break
                except FileNotFoundError:
                    # segments unlinked in the probe->map window (LRU
                    # spill raced us, before the pin landed): re-fetch —
                    # the store restores from the spill file, coming
                    # back with inline buffers
                    stored = self._store.get_stored(oid, timeout=10)
                    if stored is None:
                        break
        except BaseException:
            if pinned:
                self._unpin(oid)
            raise
        if blob is None and source is None:
            if pinned:
                self._unpin(oid)
            conn.reply(msg, found=False)
            return
        self._open_session(conn, msg, source, blob=blob, pinned=pinned)

    def _open_session(self, conn: protocol.Connection, msg: dict,
                      source, blob: Optional[bytes] = None,
                      pinned: bool = False,
                      acquire: bool = False) -> None:
        """Register a session for `source` (a chunk source / landing;
        None for blob sessions) and answer the PULL_OBJECT request.
        `acquire` takes the session's ref on the source here (the
        cut-through inline path; _source_shared pre-acquires)."""
        oid = msg["object_id"]
        # tracing plane: the serve span (pin + mapping/encode + session
        # open) parents under the puller's envelope-carried pull span,
        # putting the holder side of every transfer on the timeline
        tr = msg.get(_tp.TRACE_KEY)
        t_tr = _tp.recv_t0(msg)
        if acquire and source is not None:
            if not source.acquire():
                # lost the race with the landing's teardown: the
                # object is either sealed (next open serves the store
                # copy) or gone (puller rotates sources)
                conn.reply(msg, found=False)
                return
        if source is not None:
            size, nchunks = source.total, source.nchunks
        else:
            size, nchunks = len(blob), _nchunks(len(blob))
        pull_id = uuid.uuid4().hex[:12]
        sess = _PullSession(object_id=oid, touched=time.monotonic(),
                            blob=blob, source=source, conn_id=id(conn),
                            pinned=pinned)
        with self._slock:
            self._sessions[pull_id] = sess
            self._serves_per_object[oid] = (
                self._serves_per_object.get(oid, 0) + 1)
            while len(self._serves_per_object) > self._SERVES_PER_OBJECT_CAP:
                self._serves_per_object.pop(
                    next(iter(self._serves_per_object)))
        OBJECT_PLANE_STATS["serves_started"] += 1
        if getattr(source, "partial", False):
            OBJECT_PLANE_STATS["partial_serves"] += 1
        if t_tr is not None:
            _tp.record("serve", "serve:" + oid[:16], t_tr, _tp.now(),
                       tr[0], _tp.new_id(), tr[1],
                       {"nbytes": size})
        reply = {"found": True, "pull_id": pull_id, "nchunks": nchunks,
                 "size": size}
        if source is not None:
            reply["manifest"] = source.manifest()
        try:
            conn.reply(msg, **reply)
        except protocol.ConnectionClosed:
            with self._slock:
                self._drop_session_locked(pull_id)
            raise

    def handle_chunk(self, conn: protocol.Connection, msg: dict) -> None:
        self.sweep()
        pull_id, index = msg["pull_id"], msg["index"]
        with self._slock:
            sess = self._sessions.get(pull_id)
            if sess is not None:
                sess.touched = time.monotonic()
        if sess is None:
            conn.reply(msg, data=None)
            return
        if sess.source is not None:
            self._chunk_from_source(conn, msg, pull_id, sess, index)
            return
        blob = sess.blob
        start = index * CHUNK_BYTES
        data = blob[start:start + CHUNK_BYTES]
        OBJECT_PLANE_STATS["serve_bytes_copied"] += len(data)
        last = start + CHUNK_BYTES >= len(blob)
        if last:
            with self._slock:
                self._drop_session_locked(pull_id)
            OBJECT_PLANE_STATS["serves_completed"] += 1
        OBJECT_PLANE_STATS["serve_bytes"] += len(data)
        conn.reply(msg, data=data)

    def _chunk_from_source(self, conn: protocol.Connection, msg: dict,
                           pull_id: str, sess: _PullSession,
                           index: int) -> None:
        source = sess.source
        if index >= source.nchunks:
            conn.reply(msg, data=None)
            return
        if source.ready(index):
            self._reply_chunk(conn, msg, pull_id, source, index)
            return
        # not landed yet (cut-through): park — the landing thread
        # answers when the range arrives; a failed landing answers
        # dropped, and the child's retry/re-root machinery takes over.
        # NEVER blocks this (possibly shared read-loop) thread.
        def _fire(ok: bool, _conn=conn, _msg=msg) -> None:
            try:
                if ok:
                    self._reply_chunk(_conn, _msg, pull_id, source,
                                      index)
                else:
                    # the landing died: this session can never serve
                    # again — drop it now so its ref stops pinning the
                    # dead landing's segments until the TTL sweep
                    with self._slock:
                        self._drop_session_locked(pull_id)
                    _conn.reply(_msg, data=None)
            except protocol.ConnectionClosed:
                pass

        if not source.add_waiter(index, _fire):
            with self._slock:
                self._drop_session_locked(pull_id)
            conn.reply(msg, data=None)

    def _reply_chunk(self, conn: protocol.Connection, msg: dict,
                     pull_id: str, source, index: int) -> None:
        try:
            views = source.gather(index)
        except (FileNotFoundError, ValueError):
            conn.reply(msg, data=None)
            return
        n = sum(len(v) for v in views)
        OBJECT_PLANE_STATS["serve_bytes"] += n
        if index == source.nchunks - 1:
            with self._slock:
                self._drop_session_locked(pull_id)
            OBJECT_PLANE_STATS["serves_completed"] += 1
        conn.reply(msg, **{RAW_KEY: views})


def pull_object(conn: protocol.Connection, object_id: str,
                timeout: Optional[float] = 60.0,
                retries: Optional[int] = None,
                budget=None, store=None,
                on_first_chunk: Optional[Callable] = None,
                ) -> Optional[StoredObject]:
    """Client side: chunked fetch of one object over `conn`. With
    `store` (and RAY_TPU_PULL_MANIFEST on) the transfer asks for the
    manifest protocol and lands chunk bodies straight into pre-created
    pooled shm segments, sealing the result into `store` itself; an
    old holder's blob reply degrades transparently to the r8 path (the
    caller stores the returned object). A dropped chunk (session
    expired / holder restarted / partial relay stalled past
    pull_partial_chunk_timeout_s) re-opens the session and resumes
    from the failed index, `retries` times (default
    pull_chunk_retries). `budget`, when given, is a reserve/release
    byte-accounting object (see pull_manager): the transfer holds
    `size` of it from meta until return. `on_first_chunk(nbytes)`
    fires once when the first manifest chunk lands — the cut-through
    partial-holder registration hook."""
    if retries is None:
        retries = _CFG.pull_chunk_retries
    deadline = None if timeout is None else time.monotonic() + timeout
    want_manifest = store is not None and _CFG.pull_manifest

    def remaining() -> Optional[float]:
        if deadline is None:
            return None
        return max(0.1, deadline - time.monotonic())

    def _open_msg() -> dict:
        # stamped: the holder's serve span parents under the caller's
        # pull span (PULL_CHUNKs stay unstamped — one span per
        # session, not one per chunk)
        req = {"type": protocol.PULL_OBJECT, "object_id": object_id}
        if want_manifest:
            # per-transfer negotiation: an old holder ignores this
            # unknown key and replies with the blob protocol
            req["manifest"] = True
        return _tp.stamp(req)

    meta = conn.request(_open_msg(), timeout=remaining())
    if not meta.get("found"):
        return None
    size = meta["size"]
    nchunks = meta["nchunks"]
    manifest = meta.get("manifest") if want_manifest else None
    reserved = False
    if budget is not None:
        if not budget.reserve(size, timeout=remaining()):
            raise PullBudgetExceeded(
                f"{object_id}: {size} bytes did not fit the in-flight "
                f"budget before the deadline")
        reserved = True
    try:
        if manifest is not None:
            return _pull_manifest(conn, object_id, store, meta,
                                  retries, remaining, _open_msg,
                                  on_first_chunk)
        OBJECT_PLANE_STATS["blob_pulls"] += 1
        # Windowed chunk fetch: keep pull_pipeline_depth requests in
        # flight so the transfer is bandwidth-bound, not one-RTT-per-
        # chunk lockstep (tree broadcast compounds per-transfer latency
        # across its depth, so this matters doubly there).
        depth = max(1, _CFG.pull_pipeline_depth)
        parts: list = [None] * nchunks
        window: list[tuple[int, object]] = []   # (index, future)
        done = 0
        next_req = 0
        while done < nchunks:
            while next_req < nchunks and len(window) < depth:
                fut = conn.request_async(
                    {"type": protocol.PULL_CHUNK,
                     "pull_id": meta["pull_id"], "index": next_req})
                window.append((next_req, fut))
                next_req += 1
            idx, fut = window.pop(0)
            rep = fut.result(timeout=remaining())
            data = rep.get("data")
            if data is None:
                # session expired / holder lost it mid-pull: re-open and
                # resume from this index (chunking is deterministic).
                # Outstanding window futures reference the dead session
                # and would answer None too — discard them.
                if retries <= 0:
                    return None
                retries -= 1
                OBJECT_PLANE_STATS["chunk_retries"] += 1
                window.clear()
                next_req = idx
                meta = conn.request(_open_msg(), timeout=remaining())
                if (not meta.get("found") or meta["size"] != size
                        or meta.get("manifest") is not None):
                    return None          # gone, or a different incarnation
                continue
            if parts[idx] is None:
                done += 1
            parts[idx] = data
        blob = b"".join(parts)
        OBJECT_PLANE_STATS["land_bytes_copied"] += len(blob)
        return _decode(blob)
    finally:
        if reserved:
            budget.release(size)


def _pull_manifest(conn: protocol.Connection, object_id: str, store,
                   meta: dict, retries: int, remaining,
                   _open_msg, on_first_chunk) -> Optional[StoredObject]:
    """Manifest land loop: windowed chunk fetch writing raw bodies
    straight into the landing's segments; seals into `store` on
    completion (closing the landing->store serve gap before the
    landing leaves the table)."""
    OBJECT_PLANE_STATS["manifest_pulls"] += 1
    size = meta["size"]
    try:
        landing = Landing(store, object_id, meta["manifest"], size)
    except (ValueError, KeyError, TypeError):
        return None                  # malformed manifest: fail the source
    table = landing_table(store)
    if _CFG.pull_cut_through:
        table.put(object_id, landing)
    partial_src = bool(meta["manifest"].get("partial"))
    nchunks = landing.nchunks
    fired_first = False
    ok = False
    try:
        depth = max(1, _CFG.pull_pipeline_depth)
        window: list[tuple[int, object]] = []
        done = 0
        next_req = 0
        while done < nchunks:
            while next_req < nchunks and len(window) < depth:
                fut = conn.request_async(
                    {"type": protocol.PULL_CHUNK,
                     "pull_id": meta["pull_id"], "index": next_req})
                window.append((next_req, fut))
                next_req += 1
            idx, fut = window.pop(0)
            chunk_to = remaining()
            if partial_src:
                # a relay whose own pull stalls must cost a bounded
                # wait, then the retry/re-root machinery — not the
                # transfer's whole deadline
                cap = max(0.1, _CFG.pull_partial_chunk_timeout_s)
                chunk_to = cap if chunk_to is None else min(chunk_to,
                                                            cap)
            dropped = False
            try:
                rep = fut.result(timeout=chunk_to)
            except TimeoutError:
                left = remaining()
                if not partial_src or (left is not None
                                       and left <= 0.2):
                    raise
                dropped = True
                rep = None
            raw = None if dropped else rep.get(RAW_KEY)
            if raw is None:
                if retries <= 0:
                    return None
                retries -= 1
                OBJECT_PLANE_STATS["chunk_retries"] += 1
                window.clear()
                next_req = idx
                meta = conn.request(_open_msg(), timeout=remaining())
                man = meta.get("manifest")
                if (not meta.get("found") or man is None
                        or not landing.matches(man, meta["size"])):
                    return None          # gone, or a different incarnation
                partial_src = bool(man.get("partial"))
                continue
            try:
                fresh = landing.write_chunk(idx, raw)
            except ValueError:
                return None          # wrong-length body: corrupt source
            if fresh:
                done += 1
                if not fired_first and on_first_chunk is not None:
                    fired_first = True
                    try:
                        on_first_chunk(size)
                    except Exception:
                        pass
        stored = landing.complete()
        # seal BEFORE the landing leaves the table: a child's
        # handle_pull always finds the object in exactly one place
        store.put_stored(stored)
        OBJECT_PLANE_STATS["pull_bytes"] += stored.nbytes
        ok = True
        return stored
    finally:
        if not ok:
            landing.fail()
        table.remove(object_id, landing)
        landing.release()
