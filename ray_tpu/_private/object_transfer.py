"""Cross-host object transfer: chunked pull protocol.

The reference moves objects node-to-node with a chunked push/pull plane
(reference src/ray/object_manager/object_manager.cc, pull_manager.cc,
object_buffer_pool.cc chunking). Here the equivalent is a pull-only
protocol riding the framed-message channel:

    PULL_OBJECT {object_id}            -> {found, pull_id, nchunks, size}
    PULL_CHUNK  {pull_id, index}       -> {data: bytes}   (x nchunks)

The holder serializes the StoredObject — materializing any POSIX-shm
segments into inline bytes, since shm names are host-local — and serves
it in fixed-size chunks so one giant object never occupies a connection
for a single monolithic frame (and the puller can bound memory).

Serving side (PullServer):
- a pull session PINS its object in the local store for its lifetime
  (`pin_local`), so the LRU spill pass cannot unlink segments
  mid-transfer; if the object was ALREADY spilled (or spills in the
  probe->encode window), the serve path restores from the spill file
  and retries instead of failing the segment map;
- sessions expire after `pull_session_ttl_s`: the sweep runs lazily on
  every pull/chunk message AND on the puller's connection close, so
  pullers that die mid-pull cannot leak materialized blobs or pins;
- concurrent pulls of one object share a single encoded blob (the
  broadcast fan-out case: N children of one tree node cost one encode).

Client side (``pull_object``): a dropped/expired chunk re-opens the
session with the holder and resumes from the failed index, up to
`pull_chunk_retries` times. Transfer/serve/retry counters accumulate in
``OBJECT_PLANE_STATS`` (surfaced via the ``object_plane_stats`` state
op and node heartbeats).
"""
from __future__ import annotations

import io
import pickle
import threading
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG
from ray_tpu._private.object_store import (StoredObject, _map_segment,
                                           guard_segments)

CHUNK_BYTES = 4 * 1024 * 1024

# Process-wide object-plane counters (this process's transfers only):
# plain int increments under the GIL, same discipline as
# protocol.WIRE_STATS. Agents carry a copy on heartbeats; the head
# aggregates per node in the object_plane_stats state op.
OBJECT_PLANE_STATS = {
    "pulls_started": 0,       # transfers this process initiated
    "pulls_completed": 0,
    "pulls_failed": 0,
    "pull_bytes": 0,
    "pull_dedup_hits": 0,     # pulls that joined an in-flight transfer
    "chunk_retries": 0,       # chunk-level session re-opens
    "serves_started": 0,      # pull sessions opened by remote pullers
    "serves_completed": 0,
    "serve_bytes": 0,
    "bcast_plans": 0,         # BCAST_PLAN messages acted on (agents)
}


def materialize(obj: StoredObject) -> StoredObject:
    """Copy of `obj` with every shm-backed buffer pulled inline — the
    only form that can cross a host boundary."""
    if not obj.shm_names:
        return obj
    inline: list[bytes] = []
    ii = si = 0
    order: list[str] = []
    # guard: a concurrent refcount-zero free in this process must
    # unlink (mapping-safe), not pool-and-reuse, while we copy
    with guard_segments(obj.shm_names):
        for kind in obj.buffer_order:
            if kind == "i":
                inline.append(obj.inline_buffers[ii]); ii += 1
            else:
                mv = _map_segment(obj.shm_names[si], obj.shm_sizes[si])
                inline.append(mv.tobytes())
                del mv
                si += 1
            order.append("i")
    return StoredObject(obj.object_id, obj.payload, inline, [], [],
                        order, obj.is_error,
                        contained_ids=list(obj.contained_ids))


def _encode(obj: StoredObject) -> bytes:
    return pickle.dumps(materialize(obj), protocol=pickle.HIGHEST_PROTOCOL)


def _decode(data: bytes) -> StoredObject:
    return pickle.loads(data)


class PullBudgetExceeded(Exception):
    """The in-flight byte budget could not admit this transfer before
    the deadline — NOT a source failure (the holder is fine), so pull
    managers must not drop the location over it."""


@dataclass
class _PullSession:
    blob: bytes
    object_id: str
    touched: float
    conn_id: Optional[int] = None       # id(conn) of the puller
    pinned: bool = False


class PullServer:
    """Serves PULL_OBJECT / PULL_CHUNK against a LocalStore. Mixed into
    any endpoint that holds objects (head runtime, node agent).

    `executor` (when given) takes the slow path — spill restore from
    disk + blob encode — off the connection reader thread, so a
    multi-GB restore can never stall heartbeat processing on a shared
    control connection."""

    # bounded per-object serve-count table (object_plane_stats surface;
    # the broadcast tests assert per-node serve counts from it)
    _SERVES_PER_OBJECT_CAP = 128

    def __init__(self, store, executor=None):
        self._store = store
        self._executor = executor
        self._sessions: dict[str, _PullSession] = {}
        self._slock = threading.Lock()
        self._last_sweep = time.monotonic()
        # oid -> (weakref to the StoredObject encoded, blob): while the
        # store still holds that exact instance, concurrent sessions
        # share one encode (a re-put/restore swaps the instance, so a
        # stale blob can never be served)
        self._blob_cache: dict[str, tuple] = {}
        self._serves_per_object: dict[str, int] = {}

    # ----------------------------------------------------- sessions
    def _drop_session_locked(self, pull_id: str) -> None:
        sess = self._sessions.pop(pull_id, None)
        if sess is None:
            return
        if sess.pinned:
            self._unpin(sess.object_id)
        # last session of this object gone: release the shared blob —
        # the cache exists to amortize CONCURRENT sessions (tree
        # children), not to hold multi-GB bytes on an idle node
        if not any(s.object_id == sess.object_id
                   for s in self._sessions.values()):
            self._blob_cache.pop(sess.object_id, None)

    def _unpin(self, oid: str) -> None:
        unpin = getattr(self._store, "unpin_local", None)
        if unpin is not None:
            try:
                unpin(oid)
            except Exception:
                pass

    def sweep(self, force: bool = False) -> int:
        """Lazy TTL sweep: reap sessions idle past pull_session_ttl_s.
        Runs (throttled) on every pull/chunk message so expiry does not
        depend on further traffic for the SAME session — pullers that
        die mid-pull cannot leak materialized blobs/pins."""
        now = time.monotonic()
        if not force and now - self._last_sweep < 1.0:
            return 0
        ttl = _CFG.pull_session_ttl_s
        with self._slock:
            self._last_sweep = now
            dead = [k for k, s in self._sessions.items()
                    if now - s.touched > ttl]
            for k in dead:
                self._drop_session_locked(k)
            # blob-cache entries whose StoredObject died (deleted /
            # re-put) or that went idle are dropped with the sessions
            for oid in list(self._blob_cache):
                ref, _, created = self._blob_cache[oid]
                if ref() is None or now - created > ttl:
                    self._blob_cache.pop(oid, None)
        return len(dead)

    def on_conn_closed(self, conn) -> None:
        """Reap every session the closing connection's puller opened —
        the other half of dead-puller cleanup (the lazy sweep covers
        holders that never hear from anyone again)."""
        cid = id(conn)
        with self._slock:
            for k in [k for k, s in self._sessions.items()
                      if s.conn_id == cid]:
                self._drop_session_locked(k)

    def session_count(self) -> int:
        with self._slock:
            return len(self._sessions)

    def serves_per_object(self) -> dict[str, int]:
        with self._slock:
            return dict(self._serves_per_object)

    # ------------------------------------------------------- serving
    def handle_pull(self, conn: protocol.Connection, msg: dict) -> None:
        """Runs on the connection reader thread: answer only the cheap
        not-found case inline; ALL serving (the _encode of a possibly
        multi-GB object, and any spill restore) goes to the executor so
        the reader thread never stalls heartbeats/control traffic."""
        self.sweep()
        oid = msg["object_id"]
        stored = self._store.get_stored(oid, timeout=0, restore=False)
        if stored is None and not self._store.contains(oid):
            stored = self._store.get_stored(oid, timeout=0)
            if stored is None:
                conn.reply(msg, found=False)
                return
        if self._executor is not None:
            self._executor.submit(self._pull_slow, conn, msg, oid)
        elif stored is not None:
            self._serve(conn, msg, stored)
        else:
            self._pull_slow(conn, msg, oid)

    def _pull_slow(self, conn: protocol.Connection, msg: dict,
                   oid: str) -> None:
        try:
            stored = self._store.get_stored(oid, timeout=10)
            if stored is None:
                conn.reply(msg, found=False)
            else:
                self._serve(conn, msg, stored)
        except protocol.ConnectionClosed:
            pass

    def _encode_shared(self, stored) -> bytes:
        """Encode `stored`, sharing the blob across concurrent sessions
        of the same object while the store holds that exact instance
        (tree broadcast: fanout children of one node pay one encode)."""
        oid = stored.object_id
        with self._slock:
            ent = self._blob_cache.get(oid)
            if ent is not None and ent[0]() is stored:
                return ent[1]
        blob = _encode(stored)
        with self._slock:
            if len(self._blob_cache) >= 4:       # bounded: oldest out
                oldest = min(self._blob_cache,
                             key=lambda k: self._blob_cache[k][2])
                self._blob_cache.pop(oldest, None)
            self._blob_cache[oid] = (weakref.ref(stored), blob,
                                     time.monotonic())
        return blob

    def _serve(self, conn: protocol.Connection, msg: dict,
               stored) -> None:
        oid = stored.object_id
        # tracing plane: the serve span (pin + blob encode + session
        # open) parents under the puller's envelope-carried pull span,
        # putting the holder side of every transfer on the timeline
        tr = msg.get(_tp.TRACE_KEY)
        t_tr = _tp.recv_t0(msg)
        # Pin for the life of the session: the spill pass must not
        # unlink this object's segments (or evict the restored copy)
        # while chunks are still being read.
        pin = getattr(self._store, "pin_local", None)
        pinned = False
        if pin is not None:
            pin(oid)
            pinned = True
        blob = None
        try:
            for _attempt in range(3):
                try:
                    blob = self._encode_shared(stored)
                    break
                except FileNotFoundError:
                    # segments unlinked in the probe->map window (LRU
                    # spill raced us, before the pin landed): re-fetch —
                    # the store restores from the spill file, coming
                    # back with inline buffers
                    stored = self._store.get_stored(oid, timeout=10)
                    if stored is None:
                        break
        except BaseException:
            if pinned:
                self._unpin(oid)
            raise
        if blob is None:
            if pinned:
                self._unpin(oid)
            conn.reply(msg, found=False)
            return
        pull_id = uuid.uuid4().hex[:12]
        sess = _PullSession(blob=blob, object_id=oid,
                            touched=time.monotonic(), conn_id=id(conn),
                            pinned=pinned)
        with self._slock:
            self._sessions[pull_id] = sess
            self._serves_per_object[oid] = (
                self._serves_per_object.get(oid, 0) + 1)
            while len(self._serves_per_object) > self._SERVES_PER_OBJECT_CAP:
                self._serves_per_object.pop(
                    next(iter(self._serves_per_object)))
        OBJECT_PLANE_STATS["serves_started"] += 1
        if t_tr is not None:
            _tp.record("serve", "serve:" + oid[:16], t_tr, _tp.now(),
                       tr[0], _tp.new_id(), tr[1],
                       {"nbytes": len(blob)})
        nchunks = max(1, (len(blob) + CHUNK_BYTES - 1) // CHUNK_BYTES)
        try:
            conn.reply(msg, found=True, pull_id=pull_id, nchunks=nchunks,
                       size=len(blob))
        except protocol.ConnectionClosed:
            with self._slock:
                self._drop_session_locked(pull_id)
            raise

    def handle_chunk(self, conn: protocol.Connection, msg: dict) -> None:
        self.sweep()
        pull_id, index = msg["pull_id"], msg["index"]
        with self._slock:
            sess = self._sessions.get(pull_id)
            if sess is not None:
                blob = sess.blob
                sess.touched = time.monotonic()
        if sess is None:
            conn.reply(msg, data=None)
            return
        start = index * CHUNK_BYTES
        data = blob[start:start + CHUNK_BYTES]
        last = start + CHUNK_BYTES >= len(blob)
        if last:
            with self._slock:
                self._drop_session_locked(pull_id)
            OBJECT_PLANE_STATS["serves_completed"] += 1
        OBJECT_PLANE_STATS["serve_bytes"] += len(data)
        conn.reply(msg, data=data)


def pull_object(conn: protocol.Connection, object_id: str,
                timeout: Optional[float] = 60.0,
                retries: Optional[int] = None,
                budget=None) -> Optional[StoredObject]:
    """Client side: chunked fetch of one object over `conn`. A dropped
    chunk (session expired / holder restarted serving state) re-opens
    the session and resumes from the failed index, `retries` times
    (default pull_chunk_retries). `budget`, when given, is a
    reserve/release byte-accounting object (see pull_manager): the
    transfer holds `size` of it from meta until return."""
    if retries is None:
        retries = _CFG.pull_chunk_retries
    deadline = None if timeout is None else time.monotonic() + timeout

    def remaining() -> Optional[float]:
        if deadline is None:
            return None
        return max(0.1, deadline - time.monotonic())

    def _open_msg() -> dict:
        # stamped: the holder's serve span parents under the caller's
        # pull span (PULL_CHUNKs stay unstamped — one span per
        # session, not one per chunk)
        return _tp.stamp({"type": protocol.PULL_OBJECT,
                          "object_id": object_id})

    meta = conn.request(_open_msg(), timeout=remaining())
    if not meta.get("found"):
        return None
    size = meta["size"]
    nchunks = meta["nchunks"]
    reserved = False
    if budget is not None:
        if not budget.reserve(size, timeout=remaining()):
            raise PullBudgetExceeded(
                f"{object_id}: {size} bytes did not fit the in-flight "
                f"budget before the deadline")
        reserved = True
    try:
        # Windowed chunk fetch: keep pull_pipeline_depth requests in
        # flight so the transfer is bandwidth-bound, not one-RTT-per-
        # chunk lockstep (tree broadcast compounds per-transfer latency
        # across its depth, so this matters doubly there).
        depth = max(1, _CFG.pull_pipeline_depth)
        parts: list = [None] * nchunks
        window: list[tuple[int, object]] = []   # (index, future)
        done = 0
        next_req = 0
        while done < nchunks:
            while next_req < nchunks and len(window) < depth:
                fut = conn.request_async(
                    {"type": protocol.PULL_CHUNK,
                     "pull_id": meta["pull_id"], "index": next_req})
                window.append((next_req, fut))
                next_req += 1
            idx, fut = window.pop(0)
            rep = fut.result(timeout=remaining())
            data = rep.get("data")
            if data is None:
                # session expired / holder lost it mid-pull: re-open and
                # resume from this index (chunking is deterministic).
                # Outstanding window futures reference the dead session
                # and would answer None too — discard them.
                if retries <= 0:
                    return None
                retries -= 1
                OBJECT_PLANE_STATS["chunk_retries"] += 1
                window.clear()
                next_req = idx
                meta = conn.request(_open_msg(), timeout=remaining())
                if not meta.get("found") or meta["size"] != size:
                    return None          # gone, or a different incarnation
                continue
            if parts[idx] is None:
                done += 1
            parts[idx] = data
        return _decode(b"".join(parts))
    finally:
        if reserved:
            budget.release(size)
