"""Versioned wire codec: message dict <-> protobuf Envelope.

The schema is `ray_tpu/protos/wire.proto` (checked-in generated module
`wire_pb2.py`) — the language-neutral contract for every control-plane
frame, replacing the previous raw-pickle wire (reference parity:
src/ray/protobuf/*.proto define the reference's wire; its TaskSpec
likewise carries pickled function descriptors in `bytes` fields).

Encoding rules (exact round-trip or escape hatch, never lossy):
  * None/bool/int(:int64)/float/str/bytes/list/dict-with-str-keys whose
    size fits the structural bounds encode as typed `Value` nodes.
  * Everything else — task/actor specs, closures, exceptions, tuples,
    subclasses (IntEnum!), oversized collections — rides the `pickled`
    leaf: PLAIN pickle on the fast path (importable object graphs),
    with a tripwire falling back to cloudpickle for anything that
    needs by-value pickling (__main__ / <locals> classes, functions,
    instances — see _FastPickler). Type checks are `type() is`, not
    isinstance, so subclass identity is never silently widened.
  * Bulk collections (> _MAX_ITEMS entries, or nesting deeper than
    _MAX_DEPTH) are pickled wholesale: the structural encoding is for
    control data; the data plane stays a single opaque leaf (state-API
    replies with 100k task events must not pay a Python-loop tax).

Versioning: Envelope.version = MAJOR*100 + MINOR. A frame whose MAJOR
differs from ours raises WireVersionError — the connection is refused
before any field (in particular any pickled leaf) is decoded. MINOR
skew is compatible (proto3 skips unknown fields).

Encoding policy: messages on the language-neutral node plane (agent <->
head registration/heartbeats/events, the object-location + pull
protocol, refcounts, ping) encode field-by-field — a non-Python agent
can speak them. Python-plane messages (task dispatch, replies, nested
submission: their payloads are cloudpickled specs/closures regardless)
put the whole field dict in the flat `py_body` bytes field, keeping
the hot path within ~30% of raw pickle while every frame still carries
the versioned envelope. Structural encode/decode costs ~5µs/leaf in
Python; spending that on a task-plane frame that is ~90% pickled spec
bytes anyway buys nothing.
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Optional

import cloudpickle

from ray_tpu import native as _native
from ray_tpu._private import wire_pb2 as pb

WIRE_MAJOR = 1
WIRE_MINOR = 8          # 1: BatchFrame coalescing (negotiated by peers)
                        # 2: Envelope trace_id/parent_span (tracing
                        #    plane; old peers skip unknown fields)
                        # 3: delegated scheduling ops (NODE_LEASE_BATCH
                        #    / TASK_DONE_BATCH / lease revoke) + seq-
                        #    numbered heartbeat deltas
                        # 4: METRICS_DUMP cluster scrape (metrics
                        #    plane; no envelope change)
                        # 5: manifest pull protocol + Envelope `raw`
                        #    bulk-payload field (r12 zero-copy object
                        #    transfer) + partial-holder OBJECT_ADDED
                        # 6: wire-channel ops (ch_attach/data/ack/
                        #    close) for compiled-DAG channels (r13; no
                        #    envelope change — CH_DATA reuses `raw`)
                        # 7: NODE_DECREF_DELTA coalesced refcount
                        #    deltas (r16; no envelope change)
                        # 8: direct actor call plane (r18:
                        #    ACTOR_RESOLVE / ACTOR_TASK_DIRECT /
                        #    ACTOR_INFLIGHT_DELTA; no envelope change)
WIRE_VERSION = WIRE_MAJOR * 100 + WIRE_MINOR

# First MINOR that understands a type=="batch" Envelope carrying a
# BatchFrame of sub-frames. Senders check the peer's observed version
# (Connection.peer_wire_version) before emitting one.
BATCH_MIN_MINOR = 1
BATCH_TYPE = "batch"

# First MINOR whose Envelope schema has the trace_id/parent_span
# fields. Unlike BatchFrame these are SKIPPABLE by any proto3 peer
# (unknown fields), so the negotiation only avoids spending bytes on a
# peer that demonstrated an older MINOR (protocol.Connection strips
# the key before encode in that case).
TRACE_MIN_MINOR = 2

# First MINOR that understands the delegated-scheduling ops
# (NODE_LEASE_BATCH, NODE_TASK_DONE_BATCH, NODE_LEASE_REVOKE,
# NODE_FIND_TASK) and seq-numbered heartbeat deltas. Negotiated by
# observation like BatchFrame: senders fall back to the per-task
# protocol until the peer demonstrates MINOR >= 3.
DELEGATE_MIN_MINOR = 3

# First MINOR whose handlers answer a METRICS_DUMP request (r11
# metrics plane). An older peer would silently drop the unknown type
# and the collector's shared deadline would burn waiting on a reply
# that can never come, so the head only fans to proven peers.
METRICS_MIN_MINOR = 4

# First MINOR that understands the r12 manifest pull protocol: the
# Envelope `raw` bulk-payload field and partial-holder OBJECT_ADDED
# entries. The transfer itself negotiates per message (the puller asks
# for a manifest; an old holder ignores the unknown request key and
# serves the blob protocol — the reply shape IS the answer), so this
# constant only gates the one message an OLD receiver would
# misinterpret rather than ignore: an agent reports partial-holder
# registrations to the head only when the head demonstrated MINOR >= 5
# (an old head would record a full location for a half-landed copy).
MANIFEST_MIN_MINOR = 5

# First MINOR whose handlers speak the r13 wire-channel transport
# (experimental/wire_channel.py: CH_ATTACH/CH_DATA/CH_ACK/CH_CLOSE).
# The endpoints are new code on both sides by construction (a reader
# dials the writer's per-channel listener), so the constant gates the
# one thing an OLD peer could misread rather than ignore: a CH_DATA
# frame whose tensor rides the Envelope `raw` field. The writer emits
# raw-payload frames only toward a peer that demonstrated MINOR >= 6
# on its attach frame and falls back to the pickled body otherwise —
# negotiated by observation, the BatchFrame discipline.
CHANNEL_MIN_MINOR = 6

# First MINOR whose handlers understand a NODE_DECREF_DELTA frame
# (r16 batched decref deltas). An OLD head would silently drop the
# unknown type — every release in the frame would leak for the
# session — so agents coalesce deltas only toward a head that
# demonstrated MINOR >= 7 and fall back to forwarding the workers'
# DECREF_BATCH frames otherwise (negotiated by observation, the
# BatchFrame discipline).
DECREF_DELTA_MIN_MINOR = 7

# First MINOR whose handlers speak the direct actor call plane (r18):
# ACTOR_RESOLVE endpoint lookups, peer-dialed ACTOR_TASK_DIRECT
# submissions with inline replies, and coalesced ACTOR_INFLIGHT_DELTA
# mirror frames. An OLD peer would silently drop every one of them —
# a resolve or direct call toward it would hang its caller's future
# until the stall fallback — so callers go direct only toward peers
# that demonstrated MINOR >= 8 and stay on the head-routed actor path
# otherwise (negotiated by observation, the BatchFrame discipline).
DIRECT_ACTOR_MIN_MINOR = 8

# Message-dict carrier for the Envelope `raw` field. On encode the
# value is a LIST of buffer objects (bytes/memoryview — mapped shm
# spans) concatenated into the field by the scatter-gather emit with
# zero copies; on decode the receiver sees ONE zero-copy memoryview of
# the whole field (C parser; the protobuf fallback hands over bytes).
# Never pickled into py_body.
RAW_KEY = "_raw"

# Message-dict carrier for the Envelope trace fields: senders attach
# msg["_trace"] = (trace_id, parent_span); codecs move it between the
# dict and the proto fields so it never rides the pickled body. The
# constant lives with the tracing plane (which owns stamp()/recv_t0());
# re-exported here for the codec/protocol layer.
from ray_tpu._private.tracing_plane import TRACE_KEY  # noqa: E402

_MAX_ITEMS = 64      # larger lists/dicts -> one pickled leaf
_MAX_DEPTH = 6
_INT64_MIN = -(1 << 63)
_INT64_MAX = (1 << 63) - 1


class WireVersionError(Exception):
    """Peer speaks an incompatible wire major version."""


# Message types that encode field-by-field (the language-neutral set:
# everything a non-Python node agent / object-transfer peer needs).
# Kept in sync with protocol.py constants; anything else rides `__py__`.
STRUCTURAL_TYPES = frozenset({
    "register", "ping", "decref", "addref", "decref_batch",
    "node_decref_delta",
    "node_register", "node_heartbeat", "node_event",
    "node_kill_worker", "node_delete_object", "node_shutdown",
    "node_hb_resync",
    "object_lookup", "pull_object", "pull_chunk",
    "locate_object", "object_added", "object_removed", "bcast_plan",
})


class _NeedCloudpickle(Exception):
    """Raised mid-pickle when an object graph needs cloudpickle."""


class _FastPickler(pickle.Pickler):
    """Plain pickle with a tripwire: most control-plane messages are
    specs/dicts of importable types, which plain pickle serializes in
    ~1/6 the time of cloudpickle's reducer machinery. But plain pickle
    saves __main__ / <locals> objects BY REFERENCE — "successfully"
    producing bytes the receiving process cannot load. CPython calls
    reducer_override for every non-primitive object being saved
    (classes, functions, AND instances / global-name-pickled objects
    like a __main__ TypeVar), so any graph that needs cloudpickle's
    by-value pickling trips the wire and the whole message falls back
    to cloudpickle."""

    def reducer_override(self, obj):
        mod = getattr(obj, "__module__", None)
        if mod == "__main__" or "<locals>" in getattr(
                obj, "__qualname__", ""):
            raise _NeedCloudpickle
        if mod is None and (isinstance(obj, type) or callable(obj)):
            raise _NeedCloudpickle
        return NotImplemented


def _pickle(obj: Any) -> bytes:
    buf = io.BytesIO()
    try:
        _FastPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
        return buf.getvalue()
    except (_NeedCloudpickle, TypeError, AttributeError,
            pickle.PicklingError):
        buf = io.BytesIO()
        cloudpickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
        return buf.getvalue()


def _encode_value(obj: Any, v: pb.Value, depth: int) -> None:
    t = type(obj)
    if obj is None:
        v.null = True
    elif t is bool:
        v.b = obj
    elif t is int and _INT64_MIN <= obj <= _INT64_MAX:
        v.i = obj
    elif t is float:
        v.d = obj
    elif t is str:
        v.s = obj
    elif t is bytes:
        v.data = obj
    elif t is list and len(obj) <= _MAX_ITEMS and depth < _MAX_DEPTH:
        lv = v.list
        lv.SetInParent()                 # presence even when empty
        for item in obj:
            _encode_value(item, lv.items.add(), depth + 1)
    elif (t is dict and len(obj) <= _MAX_ITEMS and depth < _MAX_DEPTH
          and all(type(k) is str for k in obj)):
        sv = v.struct
        sv.SetInParent()                 # presence even when empty
        for k, item in obj.items():
            _encode_value(item, sv.fields[k], depth + 1)
    else:
        v.pickled = _pickle(obj)


def _decode_value(v: pb.Value) -> Any:
    kind = v.WhichOneof("kind")
    if kind == "null":
        return None
    if kind == "b":
        return v.b
    if kind == "i":
        return v.i
    if kind == "d":
        return v.d
    if kind == "s":
        return v.s
    if kind == "data":
        return v.data
    if kind == "list":
        return [_decode_value(item) for item in v.list.items]
    if kind == "struct":
        return {k: _decode_value(item)
                for k, item in v.struct.fields.items()}
    if kind == "pickled":
        return pickle.loads(v.pickled)
    return None                          # unset Value (future kinds)


def _fill_envelope(env: "pb.Envelope", msg: dict) -> None:
    mtype = msg.get("type", "")
    env.version = WIRE_VERSION
    env.type = mtype
    env.rid = msg.get("rid", 0)
    tr = msg.get(TRACE_KEY)
    if tr is not None:
        env.trace_id = tr[0]
        env.parent_span = tr[1]
    raw = msg.get(RAW_KEY)
    if raw is not None:
        # fallback codec: the field is joined (one copy); the scatter-
        # gather emit path (encode_frame_parts) is the zero-copy one
        env.raw = b"".join(raw)
    if mtype in STRUCTURAL_TYPES:
        fields = env.fields
        fields.SetInParent()
        for k, val in msg.items():
            if k == "type" or k == "rid" or k == TRACE_KEY:
                continue
            _encode_value(val, fields.fields[k], 0)
    else:
        rest = {k: v for k, v in msg.items()
                if k != "type" and k != "rid" and k != TRACE_KEY
                and k != RAW_KEY}
        if rest:
            env.py_body = _pickle(rest)


# ---- native codec fast path (r7) ----
# The hot Envelope shape — Python-plane header + opaque py_body, and
# BatchFrame assembly/splitting — can encode and decode through
# native/core.c: no protobuf message objects on the per-frame path.
# Whether that wins depends on the installed protobuf backend: against
# the pure-Python backend the C codec is ~3x; against upb/C++ the
# per-frame ctypes call overhead LOSES to protobuf's own C serializer,
# so 'auto' picks the C codec only on pure-Python-protobuf hosts
# (wire_native_codec forces either way). The structural plane
# (node-neutral field-by-field Values) and anything the C parser flags
# as irregular always stay on the real protobuf codec, which remains
# the arbiter of malformed input.

_pb_pure_python: Optional[bool] = None
_codec_memo: tuple = (-1, None)

# Pickled bodies at least this large always ride the scatter-gather
# emit (C header + body as separate iovecs): the join/serialize copy
# they'd otherwise pay dwarfs a ctypes call. Small bodies only do when
# the C codec is selected.
_ZEROCOPY_MIN_BODY = 16 * 1024


def _native_codec():
    """The native module when the C envelope codec should be used for
    dumps/loads, else None. Memoized per CONFIG generation (this runs
    per frame); flip modes in-process with env var + CONFIG.reload()."""
    global _codec_memo, _pb_pure_python
    if not _native.frame_engine_enabled():
        return None
    from ray_tpu._private.config import CONFIG
    gen = CONFIG._gen
    memo = _codec_memo
    if memo[0] == gen:
        return memo[1]
    mode = str(CONFIG.wire_native_codec).strip().lower()
    if mode in ("auto", ""):
        if _pb_pure_python is None:
            from google.protobuf.internal import api_implementation
            _pb_pure_python = api_implementation.Type() == "python"
        on = _pb_pure_python
    else:
        on = mode in ("1", "true", "yes", "on")
    eng = _native if on else None
    _codec_memo = (gen, eng)
    return eng


_FIXED64 = struct.Struct("<Q")


def _trace_tail(tr) -> bytes:
    """Protobuf bytes for the Envelope trace fields (field 7/8,
    fixed64) — appended after the py_body field, which matches the
    canonical ascending-field-number serialization exactly, so the C
    emit paths stay byte-identical to the protobuf codec. Zero values
    are omitted like proto3 does."""
    out = b""
    if tr[0]:
        out += b"\x39" + _FIXED64.pack(tr[0])
    if tr[1]:
        out += b"\x41" + _FIXED64.pack(tr[1])
    return out


def _raw_prefix(raw) -> bytes:
    """Key + length varint for the Envelope `raw` field (field 9,
    length-delimited, tag 0x4a) — the field's payload buffers follow
    as their own iovecs on the scatter-gather emit. Canonical position:
    after py_body (5) and the trace fixed64s (7/8)."""
    return b"\x4a" + _pb_varint(sum(len(b) for b in raw))


def _encode_one(msg: dict, eng=None) -> bytes:
    """Serialize ONE message to Envelope bytes (never a batch)."""
    mtype = msg.get("type", "")
    if eng is None:
        eng = _native_codec()
    if eng is not None and mtype not in STRUCTURAL_TYPES:
        rest = {k: v for k, v in msg.items()
                if k != "type" and k != "rid" and k != TRACE_KEY
                and k != RAW_KEY}
        body = _pickle(rest) if rest else b""
        data = eng.env_encode(WIRE_VERSION, mtype.encode(),
                              msg.get("rid", 0), body)
        tr = msg.get(TRACE_KEY)
        if tr is not None:
            data += _trace_tail(tr)
        raw = msg.get(RAW_KEY)
        if raw is not None:
            data += _raw_prefix(raw) + b"".join(raw)
        return data
    env = pb.Envelope()
    _fill_envelope(env, msg)
    return env.SerializeToString()


def dumps(msg: dict) -> bytes:
    """Encode a message dict as a versioned Envelope frame body."""
    if msg.get("type") == BATCH_TYPE:
        return dumps_batch(msg["frames"])
    return _encode_one(msg)


def dumps_batch(msgs: list[dict]) -> bytes:
    """Encode N message dicts as ONE BatchFrame envelope: one frame on
    the wire, N sub-frames delivered in order at the receiver. Only
    valid toward a peer that negotiated batch support (MINOR >= 1).
    The native assembly is used only when every sub-frame is Python-
    plane: structural sub-frames would each pay a separate protobuf
    serialize, where the one-shot protobuf batch encode amortizes."""
    eng = _native_codec()
    if eng is not None and all(
            m.get("type", "") not in STRUCTURAL_TYPES for m in msgs):
        subs = [_encode_one(m, eng) for m in msgs]
        return eng.batch_encode(WIRE_VERSION, BATCH_TYPE.encode(), subs)
    env = pb.Envelope(version=WIRE_VERSION, type=BATCH_TYPE)
    batch = env.batch
    batch.SetInParent()
    for msg in msgs:
        _fill_envelope(batch.frames.add(), msg)
    return env.SerializeToString()


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def encode_frame_parts(msg: dict, eng=None) -> list[bytes]:
    """ONE frame's Envelope bytes as a buffer list for scatter-gather
    emit (protocol._emit_locked -> sendmsg): [C-encoded header, pickled
    body] when the C codec is selected or the body clears the
    zero-copy threshold — the body bytes then go from the pickler to
    the kernel without ever being copied into a joined frame. A
    RAW_KEY message additionally carries its buffer list as trailing
    iovecs (the Envelope `raw` field): mapped shm spans go
    mapping -> kernel with zero Python copies. Structural/batch/other
    frames collapse to [dumps(msg)]. The buffer-list concatenation is
    byte-identical to dumps(msg)."""
    if eng is None:
        eng = _native_codec()
    mtype = msg.get("type", "")
    if mtype in STRUCTURAL_TYPES or mtype == BATCH_TYPE:
        return [dumps(msg)]
    tr = msg.get(TRACE_KEY)
    tail = _trace_tail(tr) if tr is not None else b""
    raw = msg.get(RAW_KEY)
    raw_len = sum(len(b) for b in raw) if raw is not None else 0
    rest = {k: v for k, v in msg.items()
            if k != "type" and k != "rid" and k != TRACE_KEY
            and k != RAW_KEY}
    if not rest and raw is None:
        return [dumps(msg)] if eng is None else [
            eng.env_encode_header(WIRE_VERSION, mtype.encode(),
                                  msg.get("rid", 0), 0, 0) + tail]
    body = _pickle(rest) if rest else b""
    zero_copy = (eng is not None
                 or ((len(body) >= _ZEROCOPY_MIN_BODY
                      or raw_len >= _ZEROCOPY_MIN_BODY)
                     and _native.frame_engine_enabled()))
    if not zero_copy:
        env = pb.Envelope()                   # protobuf codec, body
        env.version = WIRE_VERSION            # already pickled above
        env.type = mtype
        env.rid = msg.get("rid", 0)
        if body:
            env.py_body = body
        if tr is not None:
            env.trace_id = tr[0]
            env.parent_span = tr[1]
        if raw is not None:
            env.raw = b"".join(raw)
        return [env.SerializeToString()]
    hdr = _native.env_encode_header(WIRE_VERSION, mtype.encode(),
                                    msg.get("rid", 0),
                                    0x2A if body else 0, len(body))
    parts = [hdr, body] if body else [hdr]
    if tail:
        parts.append(tail)
    if raw is not None:
        parts.append(_raw_prefix(raw))
        parts.extend(raw)
    return parts


def encode_batch_parts(msgs: list[dict], eng=None) -> list[bytes]:
    """One BatchFrame envelope as a buffer list for scatter-gather
    emit: outer header + per-sub (frame-key prefix, sub buffers...).
    Byte-stream-identical to dumps_batch(msgs). Only used with the C
    codec selected — per-sub protobuf serializes would lose to the
    one-shot protobuf batch encode."""
    if eng is None:
        eng = _native_codec()
    if eng is None:
        return [dumps_batch(msgs)]
    parts: list[bytes] = []
    inner = 0
    for m in msgs:
        sub = encode_frame_parts(m, eng)
        sub_len = sum(len(p) for p in sub)
        pre = b"\x0a" + _pb_varint(sub_len)     # BatchFrame.frames key
        parts.append(pre)
        parts.extend(sub)
        inner += len(pre) + sub_len
    hdr = eng.env_encode_header(WIRE_VERSION, BATCH_TYPE.encode(), 0,
                                0x32, inner)
    return [hdr, *parts]


def _decode_envelope(env: "pb.Envelope") -> dict:
    if env.py_body:
        msg = pickle.loads(env.py_body)
    else:
        msg = {k: _decode_value(v)
               for k, v in env.fields.fields.items()}
    msg["type"] = env.type
    if env.rid:
        msg["rid"] = env.rid
    if env.trace_id or env.parent_span:
        msg[TRACE_KEY] = (env.trace_id, env.parent_span)
    if env.raw:
        msg[RAW_KEY] = env.raw
    return msg


def _native_decode_one(eng, data: bytes) -> Optional[dict]:
    """Decode ONE (non-batch-dispatching) envelope via the C parser.
    Returns None when the frame needs the full protobuf codec: a
    structural-plane frame (non-empty `fields`), invalid UTF-8 in
    `type`, or anything the fast parser flags as irregular."""
    view = eng.env_decode(data)
    if view is None:
        return None
    (_, rid, tbytes, body, fields_len, _, _, trace_id, parent_span,
     raw) = view
    if body:
        msg = pickle.loads(body)
    elif fields_len > 0:
        return None                  # structural plane: protobuf path
    else:
        msg = {}
    try:
        msg["type"] = tbytes.decode()
    except UnicodeDecodeError:
        return None
    if rid:
        msg["rid"] = rid
    if trace_id or parent_span:
        msg[TRACE_KEY] = (trace_id, parent_span)
    if raw is not None:
        msg[RAW_KEY] = raw
    return msg


def _native_loads_ex(eng, data: bytes) -> Optional[tuple[dict, int]]:
    """Native-codec mirror of loads_ex; None defers to protobuf."""
    view = eng.env_decode(data)
    if view is None:
        return None
    (version, rid, tbytes, body, fields_len, batch_off, batch_len,
     trace_id, parent_span, raw) = view
    if version // 100 != WIRE_MAJOR:
        raise WireVersionError(
            f"peer wire version {version} is incompatible with "
            f"ours ({WIRE_VERSION}): major "
            f"{version // 100} != {WIRE_MAJOR}")
    try:
        mtype = tbytes.decode()
    except UnicodeDecodeError:
        return None
    if mtype == BATCH_TYPE:
        frames: list[dict] = []
        if batch_off >= 0:
            spans = eng.batch_split(data, batch_off, batch_len)
            if spans is None:
                return None
            for off, length in spans:
                sub = _native_decode_one(eng, data[off:off + length])
                if sub is None:
                    # mixed batch (structural sub-frame): decode that
                    # sub with the real protobuf parser
                    sub = _decode_envelope(pb.Envelope.FromString(
                        data[off:off + length]))
                frames.append(sub)
        return {"type": BATCH_TYPE, "frames": frames}, version
    if body:
        msg = pickle.loads(body)
    elif fields_len > 0:
        return None                  # structural plane: protobuf path
    else:
        msg = {}
    msg["type"] = mtype
    if rid:
        msg["rid"] = rid
    if trace_id or parent_span:
        msg[TRACE_KEY] = (trace_id, parent_span)
    if raw is not None:
        msg[RAW_KEY] = raw
    return msg, version


def loads_ex(data: bytes) -> tuple[dict, int]:
    """Decode an Envelope frame body -> (msg, sender wire version);
    refuses foreign major versions before touching any pickled leaf.
    A type=="batch" envelope decodes to
    {"type": "batch", "frames": [msg, ...]} preserving sub-frame
    order."""
    eng = _native_codec()
    if (eng is None and len(data) >= _ZEROCOPY_MIN_BODY
            and _native.frame_engine_enabled()):
        # Large frames always take the C parser + zero-copy body view,
        # codec mode notwithstanding — the decode mirror of the
        # >=_ZEROCOPY_MIN_BODY emit rule: protobuf's FromString copies
        # a multi-MB py_body (pull chunks!) just to hand it to pickle.
        eng = _native
    if eng is not None:
        out = _native_loads_ex(eng, data)
        if out is not None:
            return out
    env = pb.Envelope.FromString(data)
    if env.version // 100 != WIRE_MAJOR:
        raise WireVersionError(
            f"peer wire version {env.version} is incompatible with "
            f"ours ({WIRE_VERSION}): major "
            f"{env.version // 100} != {WIRE_MAJOR}")
    if env.type == BATCH_TYPE:
        return ({"type": BATCH_TYPE,
                 "frames": [_decode_envelope(sub)
                            for sub in env.batch.frames]},
                env.version)
    return _decode_envelope(env), env.version


def loads(data: bytes) -> dict:
    return loads_ex(data)[0]
