"""Scheduler + worker pool: the raylet-equivalent per-node layer.

Parity map (reference src/ray/raylet/):
- ``Scheduler`` dispatch loop -> ClusterTaskManager::QueueAndScheduleTask +
  LocalTaskManager::DispatchScheduledTasksToWorkers
  (cluster_task_manager.cc:44, local_task_manager.cc:122) collapsed into one
  loop because the v0 cluster is one logical node owned by the driver.
- ``WorkerPool`` -> raylet WorkerPool (worker_pool.h:366 PopWorker): spawns
  `python -m ray_tpu._private.worker_main` subprocesses on demand up to a
  cap, reusing idle ones keyed by runtime-env hash (dispatch prefers a
  worker whose applied env already matches, and workers keep their env
  applied between same-env tasks).
- blocked-worker resource release mirrors the reference's behavior where a
  worker blocked in `ray.get` releases its CPU so the node can oversubscribe
  (avoids the classic nested-task deadlock).
- resource accounting -> ClusterResourceScheduler fixed-point math
  (common/scheduling/) simplified to float math on dicts.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ray_tpu._private import metrics_plane as _mp
from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.runtime_env import has_container
from ray_tpu._private.specs import ActorSpec, ActorTaskSpec, TaskSpec

IDLE = "idle"
BUSY = "busy"
ACTOR = "actor"
STARTING = "starting"
DEAD = "dead"

from ray_tpu._private.config import CONFIG as _CFG


@dataclass
class WorkerRec:
    worker_id: str
    proc: Optional[subprocess.Popen] = None
    conn: Optional[protocol.Connection] = None
    state: str = STARTING
    # In-flight normal tasks in dispatch (= execution) order; the worker
    # runs them FIFO on its single exec thread, so pipelining depth>1
    # overlaps the TASK_DONE round-trip with the next task's execution
    # (reference worker-lease pipelining).
    tasks: "dict[str, TaskSpec]" = field(default_factory=dict)
    # task_id -> (need, pg_key, charged): per-task resource charge so
    # completions release exactly their own share. charged=False marks
    # a task pipelined onto this worker's existing grant (reference
    # worker-lease model: a queued task reuses the lease's resources);
    # it is charged when its predecessor completes and releases them.
    task_res: dict = field(default_factory=dict)
    actor_id: Optional[str] = None
    # actor-lifetime resources (ACTOR workers only)
    acquired: dict[str, float] = field(default_factory=dict)
    # (pg_id, bundle_index) whose ledger `acquired` was charged against,
    # or None when charged against the node's free pool.
    pg_key: Optional[tuple] = None
    blocked_depth: int = 0
    started_at: float = field(default_factory=time.time)
    # hash of the runtime env last applied in this worker — dispatch
    # prefers matching workers so pooled workers skip env churn
    # (reference worker_pool.cc runtime-env-keyed reuse)
    env_hash: str = ""
    # spawned inside a container image: permanently bound to that env —
    # only exact-hash tasks may use it, and its hash never changes
    container: bool = False


def _node_memory_fraction() -> float:
    """Fraction of node memory in use (1 - MemAvailable/MemTotal)."""
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.split()[0])
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", total)
        if total <= 0:
            return 0.0
        return 1.0 - avail / total
    except OSError:
        return 0.0


def sample_host_stats(worker_pids=()) -> dict:
    """Per-node reporter sample (reference dashboard/modules/reporter):
    load, memory, and the worker pool's aggregate RSS — carried on node
    heartbeats and surfaced by the dashboard's /nodes endpoint."""
    stats: dict = {"ts": time.time(), "num_cpus": os.cpu_count(),
                   "num_workers": len(worker_pids)}
    try:
        stats["load_1m"] = round(os.getloadavg()[0], 2)
    except OSError:
        pass
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                k, _, rest = line.partition(":")
                info[k] = int(rest.split()[0])          # kB
        total = info.get("MemTotal", 0)
        avail = info.get("MemAvailable", total)
        stats["mem_total_mb"] = total // 1024
        stats["mem_available_mb"] = avail // 1024
        if total > 0:
            stats["mem_used_pct"] = round(100 * (1 - avail / total), 1)
    except OSError:
        pass
    rss = 0
    page = os.sysconf("SC_PAGE_SIZE")
    for pid in worker_pids:
        try:
            with open(f"/proc/{pid}/statm") as f:
                rss += int(f.read().split()[1]) * page
        except (OSError, ValueError, IndexError):
            pass
    stats["workers_rss_mb"] = rss // (1024 * 1024)
    return stats


def fits(avail: dict[str, float], need: dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in need.items() if v)


def acquire(avail: dict[str, float], need: dict[str, float]) -> None:
    for k, v in need.items():
        if v:
            avail[k] = avail.get(k, 0.0) - v


def release(avail: dict[str, float], got: dict[str, float]) -> None:
    for k, v in got.items():
        if v:
            avail[k] = avail.get(k, 0.0) + v




class Scheduler:
    """Per-node scheduler: task queue, resource ledger, worker pool.

    One instance per (simulated or real) node; the ClusterTaskManager
    routes work between instances and monitors their heartbeats."""

    def __init__(self, runtime, node_resources: dict[str, float],
                 listen_addr: tuple[str, int],
                 max_workers: Optional[int] = None,
                 node_id: Optional[str] = None, cluster=None):
        self._rt = runtime
        self.node_id = node_id or ("node_" + uuid.uuid4().hex[:8])
        self._cluster = cluster
        self.total = dict(node_resources)
        self.avail = dict(node_resources)
        self._addr = listen_addr
        self._max_workers = (max_workers or _CFG.worker_pool_max
                             or max(int(node_resources.get("CPU", 4)) * 2,
                                    8))
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock(f"scheduler:{self.node_id}",
                               reentrant=True)
        self._cv = threading.Condition(self._lock)
        self._pending: deque = deque()           # TaskSpec | ActorSpec
        self._queued_at: dict[int, float] = {}   # id(spec) -> enqueue time
        # Running sum of queued-but-undispatched demand, maintained on
        # every queue mutation: effective_avail() and the hybrid policy
        # read it O(1) instead of rescanning the queue (that rescan made
        # submission O(n^2) past ~1k queued tasks).
        self._pending_demand: dict[str, float] = {}
        self._last_spill_scan = 0.0
        self._workers: dict[str, WorkerRec] = {}
        # (pg_id, bundle_index) -> {"total": {...}, "avail": {...}}
        self._bundles: dict[tuple, dict] = {}
        self._running = True
        self._spawning = 0
        # Drain state (r14 preemption notice): a draining node keeps
        # running what it has but receives no NEW placements — the
        # cluster's routing (submit/spill/PG planning) skips it and its
        # queued-not-started backlog is reclaimed via reclaim_tasks.
        self.draining = False
        # Memory-pressure monitor (reference raylet memory_monitor +
        # worker_killing_policy.cc): injectable for tests.
        self.memory_fraction_fn: Callable[[], float] = \
            _node_memory_fraction
        self._last_mem_check = 0.0
        self._last_mem_kill = 0.0
        self._thread = threading.Thread(
            target=self._loop, name=f"ray-tpu-sched-{self.node_id}",
            daemon=True)

    def start(self) -> None:
        self._thread.start()

    # ---- placement-group bundle ledgers ----
    def reserve_bundle(self, pg_id: str, index: int,
                       resources: dict[str, float]) -> bool:
        """Phase-1 reserve: carve the bundle out of the node free pool."""
        with self._cv:
            if not fits(self.avail, resources):
                return False
            acquire(self.avail, resources)
            self._bundles[(pg_id, index)] = {
                "total": dict(resources), "avail": dict(resources)}
            return True

    def release_bundle(self, pg_id: str, index: int) -> None:
        """Return a bundle's unused capacity to the free pool. Resources
        held by still-running bundle workers rejoin the pool when those
        workers finish (their pg_key no longer resolves)."""
        with self._cv:
            led = self._bundles.pop((pg_id, index), None)
            if led is not None:
                release(self.avail, led["avail"])
                if self._running and self._pending:
                    self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()

    def _bundle_for(self, spec) -> Optional[tuple]:
        pg_id = getattr(spec, "placement_group_id", None)
        if not pg_id:
            return None
        idx = getattr(spec, "placement_group_bundle_index", -1)
        if idx is not None and idx >= 0:
            # The bundle may have left this node (remove_placement_group /
            # reschedule during the seconds-long worker spawn); returning
            # the key unconditionally would KeyError in dispatch and kill
            # the scheduler thread.
            return (pg_id, idx) if (pg_id, idx) in self._bundles else None
        # index -1: any bundle of this pg on this node that fits.
        need = self.need_of(spec)
        for key, led in self._bundles.items():
            if key[0] == pg_id and fits(led["avail"], need):
                return key
        # fall back to any bundle of the pg (task waits for capacity)
        for key in self._bundles:
            if key[0] == pg_id:
                return key
        return None

    # ---- submission ----
    def _demand_add(self, spec) -> None:
        for k, v in self._effective_need(spec).items():
            if v:
                self._pending_demand[k] = self._pending_demand.get(k, 0.0) + v

    def _demand_sub(self, spec) -> None:
        for k, v in self._effective_need(spec).items():
            if v:
                left = self._pending_demand.get(k, 0.0) - v
                if left > 1e-9:
                    self._pending_demand[k] = left
                else:
                    self._pending_demand.pop(k, None)

    def enqueue(self, spec) -> None:
        with self._cv:
            was_empty = not self._pending
            self._pending.append(spec)
            self._queued_at[id(spec)] = time.monotonic()
            self._demand_add(spec)
            # Inline dispatch on the submitting thread — saves a
            # scheduler-loop thread handoff (the dominant sync-RTT cost
            # on 1 core) — but ONLY when the queue was empty: with a
            # backlog, this spec cannot jump the queue, and a per-
            # enqueue scan makes bulk submission O(n^2). Completions
            # drive dispatch while a backlog exists.
            if self._running and was_empty:
                self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()

    def enqueue_many(self, specs) -> None:
        """Queue a bulk-lease batch under ONE lock acquisition with
        ONE trailing dispatch sweep (r10 delegated dispatch: a 64-spec
        lease would otherwise pay 64 lock round-trips and up to 64
        inline sweeps on the agent's head-connection reader)."""
        if not specs:
            return
        with self._cv:
            now = time.monotonic()
            for spec in specs:
                self._pending.append(spec)
                self._queued_at[id(spec)] = now
                self._demand_add(spec)
            if self._running:
                self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()

    def enqueue_front(self, spec) -> None:
        with self._cv:
            self._pending.appendleft(spec)
            self._queued_at[id(spec)] = time.monotonic()
            self._demand_add(spec)
            if self._running:
                self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()

    def cancel_pending(self, task_id: str) -> Optional[TaskSpec]:
        with self._cv:
            for spec in list(self._pending):
                if isinstance(spec, TaskSpec) and spec.task_id == task_id:
                    self._pending.remove(spec)
                    self._queued_at.pop(id(spec), None)
                    self._demand_sub(spec)
                    return spec
        return None

    # ---- worker lifecycle ----
    def spawn_worker(self, renv: Optional[dict] = None) -> WorkerRec:
        wid = "w_" + uuid.uuid4().hex[:8]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_WORKER_ID"] = wid
        env["RAY_TPU_NODE_ID"] = self.node_id
        cmd = [sys.executable, "-m", "ray_tpu._private.worker_main",
               "--addr", f"{self._addr[0]}:{self._addr[1]}",
               "--worker-id", wid]
        spawn_hash = ""
        from ray_tpu._private.runtime_env import (container_command,
                                                  has_container)
        if has_container(renv):
            # the worker process itself must start inside the image
            # (reference image_uri plugin); the worker is permanently
            # bound to this env — marked via env_hash at spawn so only
            # matching tasks reuse it
            cmd = container_command(renv, cmd)
            from ray_tpu._private.runtime_env import env_hash
            spawn_hash = env_hash(renv) or ""
        proc = subprocess.Popen(cmd, env=env)
        rec = WorkerRec(worker_id=wid, proc=proc, env_hash=spawn_hash,
                        container=bool(spawn_hash))
        with self._cv:
            self._workers[wid] = rec
            self._spawning += 1
        return rec

    def on_worker_registered(self, worker_id: str,
                             conn: protocol.Connection) -> None:
        with self._cv:
            rec = self._workers.get(worker_id)
            if rec is None:             # worker from a previous epoch
                conn.close()
                return
            rec.conn = conn
            if rec.state == STARTING:
                rec.state = IDLE
                self._spawning = max(0, self._spawning - 1)
            conn.meta["worker_id"] = worker_id
            # the driver side of a worker connection is a hot emitter
            # (TASK dispatch bursts): coalesce its fire-and-forget sends
            conn.enable_coalescing()
            self._cv.notify_all()

    def on_worker_lost(self, worker_id: str):
        """Returns (in-flight tasks, actor_id) for recovery."""
        with self._cv:
            rec = self._workers.get(worker_id)
            if rec is None or rec.state == DEAD:
                return [], None
            if rec.state == STARTING:
                self._spawning = max(0, self._spawning - 1)
            tasks, actor_id = list(rec.tasks.values()), rec.actor_id
            if rec.blocked_depth == 0:
                self._release_worker_res_locked(rec)
            rec.state = DEAD
            rec.tasks.clear()
            rec.task_res.clear()
            rec.acquired = {}
            rec.pg_key = None
            self._cv.notify_all()
            return tasks, actor_id

    # ---- aggregate per-worker resource charge (blocked release etc.)
    def _ledger_for_key(self, pg_key) -> dict[str, float]:
        if pg_key is not None:
            led = self._bundles.get(pg_key)
            if led is not None:
                return led["avail"]
        return self.avail

    def _promote_next_charge_locked(self, rec: WorkerRec) -> None:
        """Lease handoff: after a CHARGED entry leaves rec.task_res
        (completion or steal-back), charge the oldest uncharged
        successor out of the share just released — its need fits by
        the dispatch-time chain condition. While the worker is
        blocked, charges are parked: mark only; worker_unblocked
        re-acquires marked entries."""
        for tid, (need, pg_key, charged) in rec.task_res.items():
            if not charged:
                if rec.blocked_depth == 0:
                    acquire(self._ledger_for_key(pg_key), need)
                rec.task_res[tid] = (need, pg_key, True)
                break

    def _release_worker_res_locked(self, rec: WorkerRec) -> None:
        if rec.acquired:
            release(self._ledger(rec), rec.acquired)
        for need, pg_key, charged in rec.task_res.values():
            if charged:
                release(self._ledger_for_key(pg_key), need)

    def _acquire_worker_res_locked(self, rec: WorkerRec) -> None:
        if rec.acquired:
            acquire(self._ledger(rec), rec.acquired)
        for need, pg_key, charged in rec.task_res.values():
            if charged:
                acquire(self._ledger_for_key(pg_key), need)

    def heartbeat_snapshot(self) -> dict:
        """Consistent copies of the ledgers a node heartbeat reports —
        taken under the scheduler lock so a concurrent dispatch can't
        mutate the dicts mid-serialization."""
        with self._lock:
            snap = {
                "avail": dict(self.avail),
                "total": dict(self.total),
                "pending_demand": dict(self._pending_demand),
                "pending_shapes": self.pending_shapes(),
                "is_idle": self.is_idle(),
            }
            pids = [r.proc.pid for r in self._workers.values()
                    if r.proc is not None]
        snap["host_stats"] = sample_host_stats(pids)
        snap["workers"] = self.workers_snapshot()
        return snap

    def host_stats(self) -> dict:
        """Reporter sample alone (for the head's own list_nodes view) —
        avoids copying the full resource ledgers heartbeat_snapshot
        builds."""
        with self._lock:
            pids = [r.proc.pid for r in self._workers.values()
                    if r.proc is not None]
        return sample_host_stats(pids)

    def workers_snapshot(self) -> list[dict]:
        """Worker-manager table rows (reference GcsWorkerManager /
        worker_pool.cc state): one dict per pooled worker."""
        now = time.time()
        with self._lock:
            return [{
                "worker_id": r.worker_id,
                "pid": r.proc.pid if r.proc is not None else None,
                "state": r.state,
                "actor_id": r.actor_id,
                "inflight_tasks": len(r.tasks),
                "blocked_depth": r.blocked_depth,
                "env_hash": r.env_hash,
                "age_s": round(now - r.started_at, 1),
                # which wire engine the worker registered with (r7):
                # a mixed-mode fleet is a perf-debugging smell
                "wire_native": (r.conn.meta.get("wire_native")
                                if r.conn is not None else None),
                # r18 worker-direct serving socket (None: no listener)
                "direct_port": (r.conn.meta.get("direct_port")
                                if r.conn is not None else None),
            } for r in self._workers.values()]

    def direct_port_of(self, worker_id: str):
        """The worker's r18 direct-serving port (None when it has no
        listener or is gone) — resolve-time input for the direct
        actor call plane."""
        with self._lock:
            rec = self._workers.get(worker_id)
            if rec is None or rec.state == DEAD or rec.conn is None:
                return None
            return rec.conn.meta.get("direct_port")

    def worker_running_task(self, task_id: str):
        """(worker_id, spec) currently executing (or queued in) the
        worker that holds task_id, or None."""
        with self._lock:
            for rec in self._workers.values():
                if rec.state == BUSY and task_id in rec.tasks:
                    return rec.worker_id, rec.tasks[task_id]
        return None

    def cancel_running(self, worker_id: str, task_id: str) -> bool:
        with self._lock:
            rec = self._workers.get(worker_id)
        if rec is None or rec.conn is None:
            return False
        try:
            rec.conn.send({"type": protocol.CANCEL_TASK,
                           "task_id": task_id})
            return True
        except protocol.ConnectionClosed:
            return False

    def kill_worker(self, worker_id: str) -> None:
        with self._lock:
            rec = self._workers.get(worker_id)
        if rec is None:
            return
        if rec.conn is not None:
            try:
                rec.conn.send({"type": protocol.SHUTDOWN})
            except Exception:
                pass
        if rec.proc is not None:
            try:
                rec.proc.terminate()
            except Exception:
                pass

    # ---- blocked-worker accounting ----
    def worker_blocked(self, worker_id: str) -> None:
        steal: list[str] = []
        with self._cv:
            rec = self._workers.get(worker_id)
            if rec is None:
                return
            rec.blocked_depth += 1
            if rec.blocked_depth == 1 and (rec.acquired or rec.task_res):
                self._release_worker_res_locked(rec)
                # freed resources: start queued work immediately
                if self._running and self._pending:
                    self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            # Steal back tasks pipelined BEHIND the now-blocked task:
            # the worker executes FIFO on one thread, so they cannot
            # start until the blocked get returns — and if that get
            # transitively depends on one of them (nested submission),
            # that is a deadlock, not just a stall.
            if len(rec.tasks) > 1 and rec.conn is not None:
                steal = list(rec.tasks.keys())[1:]
            self._cv.notify_all()
        for tid in steal:
            self._steal_queued_task(rec, tid)

    def _pop_worker_task_locked(self, rec: WorkerRec,
                                task_id: str) -> Optional[TaskSpec]:
        """UNQUEUE accounting shared by the steal-back and lease-
        reclaim paths: remove a worker-confirmed-unstarted task from
        rec's FIFO mirror and settle its resource charge. Caller holds
        the lock and has an ``ok`` UNQUEUE reply in hand; returns the
        spec, or None when the record went stale (worker replaced /
        task already gone)."""
        cur = self._workers.get(rec.worker_id)
        if cur is not rec:
            return None
        spec = rec.tasks.pop(task_id, None)
        need_pg = rec.task_res.pop(task_id, None)
        if spec is None:
            return None
        if need_pg is not None and need_pg[2]:
            if rec.blocked_depth == 0:
                # the worker unblocked between steal and reply, so its
                # charges were re-acquired — release this one
                # (uncharged pipelined tasks never held a share)
                release(self._ledger_for_key(need_pg[1]), need_pg[0])
            # a charged entry left the chain: hand its share to the
            # next queued task, or the rest of the pipeline would run
            # permanently uncharged
            self._promote_next_charge_locked(rec)
        if rec.state == BUSY and not rec.tasks:
            rec.state = IDLE
        return spec

    def _steal_queued_task(self, rec: WorkerRec, task_id: str) -> None:
        """Ask the worker to drop a not-yet-started pipelined task from
        its local FIFO and requeue it here. Runs async: this path is
        reached on the worker connection's reader thread, so a blocking
        request would deadlock against our own reply."""
        try:
            fut = rec.conn.request_async(
                {"type": protocol.UNQUEUE_TASK, "task_id": task_id})
        except protocol.ConnectionClosed:
            return

        def _done(f) -> None:
            try:
                rep = f.result(0)
            except BaseException:
                return                # worker died: death path requeues
            if not rep.get("ok"):
                return                # already started: FIFO handles it
            with self._cv:
                spec = self._pop_worker_task_locked(rec, task_id)
                if spec is None:
                    return
                self._pending.appendleft(spec)
                self._queued_at[id(spec)] = time.monotonic()
                self._demand_add(spec)
                if self._running:
                    self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
                self._cv.notify_all()

        fut.add_done_callback(_done)

    def find_task(self, task_id: str):
        """Where a task currently lives on this node: ("pending", None)
        while queued here, ("running", worker_id) while in a worker's
        FIFO (dispatched; possibly not yet started), else None. The
        head's cancel path uses this in delegated mode, where per-task
        dispatch events are suppressed."""
        with self._lock:
            for spec in self._pending:
                if getattr(spec, "task_id", None) == task_id:
                    return ("pending", None)
            for rec in self._workers.values():
                if rec.state != DEAD and task_id in rec.tasks:
                    return ("running", rec.worker_id)
        return None

    def reclaim_tasks(self, task_ids: list,
                      callback: Callable[[list], None]) -> None:
        """Lease revoke (r10): pull queued-NOT-started tasks back out
        of this node and hand their specs to `callback` in one shot.
        Pending-queue entries come out synchronously; tasks already
        pipelined into a worker's FIFO go through the r6 UNQUEUE_TASK
        tombstone machinery (async — the worker refuses if the task
        started, in which case it stays leased here and runs to
        completion). `callback(reclaimed_specs)` fires exactly once,
        after every worker probe resolves."""
        reclaimed: list = []
        probes: list = []               # (rec, task_id, future)
        want = set(task_ids)
        with self._cv:
            # ONE pass over the queue and worker FIFOs builds the id
            # indexes — per-id rescans of a 10k-deep backlog (exactly
            # the state that triggers a rebalance revoke) would stall
            # dispatch under this lock for the whole sweep
            pending_hits = {}
            for spec in self._pending:
                tid = getattr(spec, "task_id", None)
                if tid in want:
                    pending_hits[tid] = spec
            if pending_hits:
                # one rebuild, not a deque.remove per id (each remove
                # rescans from the front — the same O(ids x backlog)
                # this index exists to avoid)
                drop = set(map(id, pending_hits.values()))
                self._pending = deque(
                    s for s in self._pending if id(s) not in drop)
                for spec in pending_hits.values():
                    self._queued_at.pop(id(spec), None)
                    self._demand_sub(spec)
                    reclaimed.append(spec)
            worker_hits = {}
            for rec in self._workers.values():
                if rec.state == DEAD or rec.conn is None:
                    continue
                # FIFO head = (likely) already executing; only the
                # queued tail is reclaimable
                it = iter(rec.tasks)
                next(it, None)
                for tid in it:
                    if tid in want:
                        worker_hits[tid] = rec
            for tid in task_ids:
                if tid in pending_hits:
                    continue
                rec = worker_hits.get(tid)
                if rec is not None:
                    try:
                        fut = rec.conn.request_async(
                            {"type": protocol.UNQUEUE_TASK,
                             "task_id": tid})
                        probes.append((rec, tid, fut))
                    except protocol.ConnectionClosed:
                        pass
        if not probes:
            callback(reclaimed)
            return
        state = {"left": len(probes)}
        state_lock = threading.Lock()

        def _probe_done(rec, tid, fut) -> None:
            try:
                ok = bool(fut.result(0).get("ok"))
            except BaseException:
                ok = False              # worker died: death path covers
            if ok:
                with self._cv:
                    spec = self._pop_worker_task_locked(rec, tid)
                    if spec is not None:
                        reclaimed.append(spec)
                    self._cv.notify_all()
            with state_lock:
                state["left"] -= 1
                last = state["left"] == 0
            if last:
                callback(reclaimed)

        for rec, tid, fut in probes:
            fut.add_done_callback(
                lambda f, rec=rec, tid=tid: _probe_done(rec, tid, f))

    def worker_unblocked(self, worker_id: str) -> None:
        with self._cv:
            rec = self._workers.get(worker_id)
            if rec is None:
                return
            rec.blocked_depth = max(0, rec.blocked_depth - 1)
            if (rec.blocked_depth == 0 and rec.state != DEAD
                    and (rec.acquired or rec.task_res)):
                # Re-acquire (may oversubscribe transiently, as the reference
                # raylet does when a blocked worker resumes).
                self._acquire_worker_res_locked(rec)

    # ---- completion ----
    def task_finished(self, worker_id: str,
                      task_id: Optional[str] = None) -> Optional[TaskSpec]:
        with self._cv:
            rec = self._workers.get(worker_id)
            if rec is None:
                return None
            if task_id is None and rec.tasks:   # legacy callers: FIFO
                task_id = next(iter(rec.tasks))
            task = rec.tasks.pop(task_id, None) if task_id else None
            need_pg = rec.task_res.pop(task_id, None) if task_id else None
            if need_pg is not None and need_pg[2]:
                if rec.blocked_depth == 0:
                    release(self._ledger_for_key(need_pg[1]), need_pg[0])
                self._promote_next_charge_locked(rec)
            if rec.state == BUSY and not rec.tasks:
                rec.state = IDLE
            # Dispatch the next queued specs NOW, on the completion
            # reader thread, instead of bouncing through the loop
            # thread — but with refill hysteresis: only sweep once this
            # worker has >= 2 free pipeline slots (or went idle), so
            # replacements leave as multi-spec burst frames and the
            # worker's back-to-back completions coalesce, instead of
            # the per-completion lock-step that emits single TASK and
            # TASK_DONE frames. Halves the sweeps per task too. The
            # 20 Hz loop tick remains the convergence backstop.
            # floor of 1: at depth <= 2 every completion refills (the
            # pre-hysteresis behavior), else the last slot would only
            # refill via the 20 Hz backstop — a round-trip bubble
            depth = _CFG.worker_pipeline_depth
            if (self._running and self._pending
                    and (rec.state != BUSY
                         or len(rec.tasks) <= max(depth - 2, 1))):
                self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()
            return task

    def actor_ready(self, worker_id: str) -> None:
        with self._cv:
            if self._running and self._pending:
                self._try_dispatch_locked(self._INLINE_SCAN_LIMIT)
            self._cv.notify_all()

    # ---- dispatch loop ----
    @staticmethod
    def _spec_env_hash(spec) -> str:
        """Cached on the spec: the dispatch loop rescans queued specs
        every pass and must not re-serialize envs each time."""
        h = getattr(spec, "_env_hash_cache", None)
        if h is None:
            from ray_tpu._private.runtime_env import env_hash
            h = env_hash(getattr(spec, "runtime_env", None)) or ""
            try:
                spec._env_hash_cache = h
            except AttributeError:
                pass
        return h

    @staticmethod
    def _spec_need_key(spec) -> tuple:
        """Cached hashable shape of a spec's resource need (r16 sweep
        miss-memo key component). Resources never change after
        submission, so the tuple is computed once per spec — a full
        sweep over a 100k backlog must not rebuild it per pass."""
        k = getattr(spec, "_need_key_cache", None)
        if k is None:
            k = tuple(sorted(Scheduler.need_of(spec).items()))
            try:
                spec._need_key_cache = k
            except AttributeError:
                pass
        return k

    def _pick_worker(self, spec=None) -> Optional[WorkerRec]:
        """Idle worker, preferring one whose last applied runtime env
        matches the spec's (runtime-env-keyed reuse). Pipelining onto a
        BUSY worker is the dispatch sweep's job (_pick_piggyback): it
        rides the worker's lease uncharged, so a worker never holds
        more than one resource charge — keeping spare capacity visible
        to idle/new workers instead of concentrating charges on a few
        pipelines."""
        want = "" if spec is None else self._spec_env_hash(spec)
        # container tasks can only run in a worker SPAWNED inside the
        # image (exact env-hash match); plain workers can't adopt one
        exact_only = spec is not None and has_container(
            getattr(spec, "runtime_env", None))
        fallback = None
        for rec in self._workers.values():
            if rec.conn is None:
                continue
            if rec.container and rec.env_hash != want:
                continue    # image-bound: invisible to other tasks
            if rec.state == IDLE:
                if rec.env_hash == want:
                    return rec
                if fallback is None and not exact_only:
                    fallback = rec
        return fallback

    def _refillable_locked(self) -> set:
        """Workers a dispatch sweep may pipeline onto: non-BUSY, or
        BUSY with >= 2 free pipeline slots. Snapshotted at sweep start
        and kept for the whole sweep, so an eligible worker is topped
        up to FULL depth in one multi-spec burst while a worker one
        task short of full is left alone — per-completion single-frame
        refills (which defeat wire coalescing) cannot happen."""
        depth = _CFG.worker_pipeline_depth
        floor = max(depth - 2, 1)
        return {wid for wid, rec in self._workers.items()
                if rec.state != BUSY or len(rec.tasks) <= floor}

    def _pick_piggyback(self, spec, need: dict[str, float],
                        pg_key, eligible: set) -> Optional[WorkerRec]:
        """Saturation-path pipelining (reference worker-lease model):
        when the free pool cannot cover `need`, a normal task may still
        queue FIFO on a BUSY same-env worker, riding that worker's
        existing resource grant — uncharged until the task ahead of it
        completes and hands its share over (task_finished). Sound
        because of the dispatch-time chain condition: the task's need
        fits inside its immediate predecessor's on the same ledger, so
        the predecessor's release always covers the successor's
        acquire."""
        if isinstance(spec, ActorSpec):
            return None
        if getattr(spec, "placement_group_id", None):
            # PG tasks keep queue-or-fail semantics: pipelining one
            # behind a bundle's occupant would dodge the pending-queue
            # sweep that fails it fast on remove_placement_group, and
            # its lease hand-off would straddle a bundle ledger that
            # can be torn down mid-chain.
            return None
        depth = _CFG.worker_pipeline_depth
        if depth <= 1:
            return None
        want = self._spec_env_hash(spec)
        for rec in self._workers.values():
            if (rec.conn is None or rec.state != BUSY
                    or rec.worker_id not in eligible
                    or rec.blocked_depth > 0 or rec.env_hash != want
                    or len(rec.tasks) >= depth or not rec.task_res):
                continue
            last_need, last_pg, _ = next(reversed(rec.task_res.values()))
            if last_pg != pg_key:
                continue            # predecessor charges another ledger
            if all(last_need.get(k, 0.0) >= v for k, v in need.items()):
                return rec
        return None

    def _alive_count(self) -> int:
        return sum(1 for r in self._workers.values() if r.state != DEAD)

    @staticmethod
    def need_of(spec) -> dict[str, float]:
        res = dict(spec.resources) if spec.resources else {}
        if "CPU" not in res and not res.get("_pg_reserved"):
            res.setdefault("CPU", 1.0)
        res.pop("_pg_reserved", None)
        return res

    def _effective_need(self, spec) -> dict[str, float]:
        return self.need_of(spec)

    def effective_avail(self) -> dict[str, float]:
        """Availability minus demand already queued here but not yet
        dispatched (workers take seconds to spawn, so `avail` alone
        wildly overstates capacity during placement bursts)."""
        with self._lock:
            eff = dict(self.avail)
            for k, v in self._pending_demand.items():
                eff[k] = eff.get(k, 0.0) - v
            return eff

    def pending_shapes(self) -> list[dict[str, float]]:
        """Resource shapes of queued specs beyond current availability
        (autoscaler demand units): simulate dispatch against a copy of
        avail; what doesn't fit is unmet demand."""
        with self._lock:
            eff = dict(self.avail)
            unmet = []
            for spec in self._pending:
                need = self._effective_need(spec)
                if fits(eff, need):
                    acquire(eff, need)
                else:
                    unmet.append(need)
            return unmet

    def set_draining(self, flag: bool = True) -> None:
        """Flip drain state (drain-before-kill, r14). Routing decisions
        live cluster-side; this flag is what they consult. Dispatch of
        already-queued work continues — the cluster reclaims what it
        wants moved via ``reclaim_tasks`` and leaves the rest to finish
        here before the node is released."""
        self.draining = bool(flag)

    def queued_task_ids(self, limit: int = 1 << 20) -> list:
        """Task ids of queued-NOT-(necessarily-)started work on this
        node: the pending queue plus each worker FIFO's tail (the head
        entry is likely already executing). The drain path feeds these
        to ``reclaim_tasks`` — the local-scheduler analogue of the
        delegated ``steal_candidates`` (r10). Movable work only: actor
        calls are bound to their actor's worker, and affinity/PG-
        pinned specs would just be re-routed straight back here."""
        def _movable(spec) -> bool:
            return (isinstance(spec, TaskSpec)
                    and not getattr(spec, "node_id", None)
                    and not getattr(spec, "placement_group_id", None))

        ids: list = []
        with self._lock:
            for spec in self._pending:
                tid = getattr(spec, "task_id", None)
                if tid is not None and _movable(spec):
                    ids.append(tid)
            for rec in self._workers.values():
                if rec.state == DEAD:
                    continue
                it = iter(rec.tasks.items())
                next(it, None)
                ids.extend(tid for tid, spec in it if _movable(spec))
        return ids[:limit]

    def known_task_ids(self) -> list:
        """EVERY plain-task id this node currently holds: the pending
        queue plus every worker-FIFO entry, including the (likely
        executing) head of each FIFO. The agent's head-restart rejoin
        report is built from this (r15): a rehydrated head re-places
        only mirrored tasks the agent does NOT know — resubmitting a
        task that is queued, running, or finishing here would break
        exactly-once."""
        ids: list = []
        with self._lock:
            for spec in self._pending:
                tid = getattr(spec, "task_id", None)
                if tid is not None:
                    ids.append(tid)
            for rec in self._workers.values():
                if rec.state == DEAD:
                    continue
                ids.extend(rec.tasks.keys())
        return ids

    def is_idle(self) -> bool:
        """Nothing queued, nothing running, no PG bundles, full
        availability — evaluated atomically (autoscaler scale-down)."""
        with self._lock:
            if self._pending or self._bundles or self._spawning:
                return False
            if any(r.state in (BUSY, ACTOR) for r in
                   self._workers.values()):
                return False
            return all(abs(self.avail.get(k, 0.0) - v) < 1e-6
                       for k, v in self.total.items())

    @staticmethod
    def utilization_from(eff: dict[str, float],
                         total: dict[str, float]) -> float:
        """utilization() over a caller-held effective_avail snapshot —
        the hybrid selection loop takes ONE snapshot per node and
        derives both its fits() check and this from it, instead of
        re-taking the hot scheduler lock for every phase."""
        u = 0.0
        for k, tot in total.items():
            if tot > 0:
                u = max(u, 1.0 - eff.get(k, 0.0) / tot)
        return u

    def utilization(self) -> float:
        """Max per-resource utilization fraction incl. queued demand
        (hybrid-policy input; may exceed 1.0 under backlog)."""
        return self.utilization_from(self.effective_avail(), self.total)

    def live_actors(self) -> dict[str, str]:
        """actor_id -> worker_id for actors with a live worker here —
        reported to the head when this agent rejoins after a head
        restart, so rehydrated actor records re-attach to their
        still-running workers instead of restarting them."""
        with self._lock:
            return {r.actor_id: r.worker_id
                    for r in self._workers.values()
                    if r.actor_id is not None and r.state != DEAD}

    def owns_worker(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._workers

    def _ledger(self, rec: WorkerRec) -> dict[str, float]:
        """The availability pool `rec.acquired` was charged against. A
        bundle released while its workers still run falls back to the
        node pool (the bundle's ledger is gone)."""
        return self._ledger_for_key(rec.pg_key)

    def _loop(self) -> None:
        """Periodic dispatch backstop. Inline dispatch (enqueue/
        completion/unblock paths) handles the hot path, so this thread
        deliberately does NOT wake on queue notifies — per-event wakeups
        made it re-sweep the whole backlog on every task (O(n^2) drain,
        ~600us of head CPU per task). It ticks on a fixed cadence with a
        bounded sweep, and runs the unbounded convergence sweep (deep
        queues, odd resource shapes) every ~2s."""
        last_full = 0.0
        while True:
            with self._cv:
                if not self._running:
                    return
                if self._cluster is not None:
                    self._cluster.heartbeat(self.node_id)
                self._reap_failed_spawns_locked()
                self._spill_aged_locked()
                now = time.monotonic()
                if now - last_full >= 2.0:
                    self._try_dispatch_locked()
                    last_full = now
                else:
                    self._try_dispatch_locked(512)
            try:
                self._memory_monitor_step()
            except Exception:
                pass          # the dispatch backstop must never die
            time.sleep(0.05)

    # ------------------------------------------------ memory pressure
    def _memory_monitor_step(self) -> None:
        """Kill a task worker when node memory usage crosses the
        threshold (reference raylet memory monitor). Victim selection is
        the reference's retriable-FIFO policy
        (worker_killing_policy.cc): retriable task workers first,
        newest-started first — the cheapest work to redo — and never
        actors (their loss cascades)."""
        threshold = _CFG.memory_monitor_threshold
        if threshold <= 0 or not self._running:
            return
        now = time.monotonic()
        if now - self._last_mem_check < _CFG.memory_monitor_refresh_s:
            return
        self._last_mem_check = now
        try:
            frac = self.memory_fraction_fn()
        except Exception:
            return
        if frac < threshold:
            return
        # cooldown: a kill takes seconds to actually release memory —
        # without it, sustained (possibly external) pressure would
        # massacre every worker within a few ticks
        cooldown = max(5.0, 3 * _CFG.memory_monitor_refresh_s)
        if now - self._last_mem_kill < cooldown:
            return
        with self._lock:
            candidates = [r for r in self._workers.values()
                          if r.state == BUSY and r.conn is not None
                          and r.tasks]
            if not candidates:
                return

            def retriable(rec: WorkerRec) -> bool:
                return all(t.retries_used < t.max_retries
                           for t in rec.tasks.values())

            pool = [r for r in candidates if retriable(r)] or candidates
            victim = max(pool, key=lambda r: r.started_at)
            names = [t.name or t.task_id
                     for t in victim.tasks.values()]
            victim_id = victim.worker_id
        self._last_mem_kill = now
        sys.stderr.write(
            f"ray_tpu: node {self.node_id} memory usage "
            f"{frac:.0%} >= {threshold:.0%}; killing worker "
            f"{victim_id} (tasks: {names}) to relieve "
            f"pressure — retriable tasks will be retried\n")
        self.kill_worker(victim_id)

    def _spill_aged_locked(self) -> None:
        """Spillback (stage-1 redirect): hand unconstrained tasks that
        aged past the spill_delay_s knob without resources back to the cluster
        for re-placement on a node with room."""
        if self._cluster is None:
            return
        now = time.monotonic()
        # Throttle: the scan is O(queue) with dict churn per spec; at
        # most ~4 scans/s, and none when there is nowhere to spill to.
        # NOTE: the node lock is held here — only the cluster's
        # LOCK-FREE node count may be read (cluster-lock calls from
        # under a node lock are the ABBA deadlock _fail_if_pg_removed
        # documents).
        if now - self._last_spill_scan < 0.25:
            return
        if self._cluster.alive_node_count() <= 1:
            return
        self._last_spill_scan = now
        for spec in list(self._pending):
            # The lock is dropped around try_spill below, so a concurrent
            # cancel_pending may have removed a later snapshot entry.
            if id(spec) not in self._queued_at:
                continue
            if fits(self.avail, self._effective_need(spec)):
                continue
            t0 = self._queued_at.get(id(spec))
            if t0 is None or now - t0 < _CFG.spill_delay_s:
                continue
            spilled = getattr(spec, "_spill_count", 0)
            if spilled >= 3:
                continue
            # Release the lock around the cluster call (it takes the
            # cluster lock; cluster->node calls take node locks).
            self._pending.remove(spec)
            self._queued_at.pop(id(spec), None)
            self._demand_sub(spec)
            self._cv.release()
            try:
                try:
                    spec._spill_count = spilled + 1
                except AttributeError:
                    pass
                moved = self._cluster.try_spill(spec, self.node_id)
            finally:
                self._cv.acquire()
            if not moved:
                self._pending.appendleft(spec)
                self._queued_at[id(spec)] = t0
                self._demand_add(spec)

    def _reap_failed_spawns_locked(self) -> None:
        """A worker that exits (or hangs) before registering would otherwise
        hold a _spawning slot forever and stall dispatch permanently."""
        now = time.time()
        for rec in self._workers.values():
            if rec.state != STARTING:
                continue
            exited = rec.proc is not None and rec.proc.poll() is not None
            timed_out = now - rec.started_at > _CFG.worker_spawn_timeout_s
            if exited or timed_out:
                rec.state = DEAD
                self._spawning = max(0, self._spawning - 1)
                sys.stderr.write(
                    f"ray_tpu: worker {rec.worker_id} failed to start "
                    f"({'exited' if exited else 'timed out'})\n")
                if timed_out and rec.proc is not None:
                    try:
                        rec.proc.kill()
                    except Exception:
                        pass

    # Inline (event-triggered) dispatches scan at most this many queued
    # specs: one enqueue/completion can enable at most ~one dispatch at
    # the queue head, and an unbounded scan over a long queue of
    # non-fitting specs made hot-path submission O(n^2). The loop
    # thread's periodic full sweep remains the convergence backstop.
    _INLINE_SCAN_LIMIT = 64

    @staticmethod
    def _send_dispatch_outbox(outbox: list,
                              eager: bool = False) -> None:
        """Ship the sweep's accumulated (conn, msg) dispatches through
        each worker connection's coalescing queue: the flusher thread
        pays the encode+sendall (keeping it off the submitting/
        completion-handling thread — it was ~35% of per-submit head CPU)
        and adjacent dispatches to one worker ride ONE BatchFrame. Must
        run BEFORE the scheduler lock is dropped: the steal-back path
        (worker_blocked) takes the lock and sends UNQUEUE_TASK eagerly,
        which flushes the queue first — a TASK parked here can never be
        overtaken, but it must already BE in the queue by then.

        ``eager`` (r18 sync-latency triage): a LONE dispatch with an
        empty queue behind it is a sync round-trip, not a burst — the
        coalescing window would charge it ~wire_batch_delay_ms of pure
        latency for nothing (the submitting thread is about to block
        in get() anyway), the same reasoning as the worker's lone-
        completion eager TASK_DONE. Bursts keep the lazy path: under a
        drain the queue is non-empty and the flusher amortizes."""
        if not outbox:
            return
        for conn, msg in outbox:
            try:
                if eager:
                    conn.send(msg)
                else:
                    conn.send_lazy(msg)
            except protocol.ConnectionClosed:
                pass      # worker-death recovery requeues its tasks
        outbox.clear()

    def _try_dispatch_locked(self, scan_limit: Optional[int] = None
                             ) -> bool:
        """One sweep over the queue, dispatching EVERY spec a free
        worker + resources allow (a per-dispatch rescan made draining n
        queued tasks O(n^2); reference LocalTaskManager::
        DispatchScheduledTasksToWorkers drains its queue per wake the
        same way). `scan_limit` bounds the sweep for inline callers.
        Dispatch frames accumulate in an outbox and ship per-connection
        at the end of the sweep (or before any mid-sweep lock drop)."""
        dispatched = 0
        outbox: list = []
        refillable = self._refillable_locked()
        if scan_limit is None:
            snapshot = list(self._pending)
        else:
            import itertools as _it
            snapshot = list(_it.islice(self._pending, scan_limit))
        # r16 saturated-sweep miss memo: once a plain (no-PG, no-actor)
        # spec of a given (env, need-shape) found neither pool room nor
        # a piggyback slot, every later same-shape spec in THIS sweep
        # skips on one set lookup. Sound within a sweep, including for
        # incomparable multi-resource shapes: (a) fits() cannot start
        # succeeding — the pool only shrinks under the held lock
        # (dispatches acquire, nothing releases; completions need this
        # lock). (b) A piggyback slot for missed shape S cannot open —
        # it requires a worker whose LAST queued need D >= S
        # componentwise (the chain condition), and any mid-sweep
        # dispatch of such a D either passed fits(D) on a pool smaller
        # than the one fits(S) already failed on (D >= S makes that a
        # contradiction) or itself piggybacked behind some P >= D >= S
        # on a worker whose eligibility cannot have improved since S's
        # probe (the eligible set is fixed, FIFO depth only grows
        # mid-sweep, blocked_depth needs this lock). Without the memo,
        # the 2 s full-sweep backstop over a saturated 100k backlog
        # paid O(n) worker probes per pass — head cost proportional to
        # the in-flight population, the very thing r16 removes.
        misses: set = set()
        for spec in snapshot:
            if id(spec) not in self._queued_at:
                continue              # removed while the lock was dropped
            pg_key = self._bundle_for(spec)
            if getattr(spec, "placement_group_id", None) and pg_key is None:
                self._send_dispatch_outbox(outbox)   # next call drops lock
                self._fail_if_pg_removed(spec)
                continue                  # bundle not (yet) on this node
            mkey = None
            if pg_key is None:
                # one cached tuple per spec: the memo probe must cost
                # a getattr + set hit, not an env-hash + need rebuild,
                # or scanning a deep backlog stays expensive
                mkey = getattr(spec, "_sweep_key_cache", None)
                if mkey is None and not isinstance(spec, ActorSpec):
                    mkey = (self._spec_env_hash(spec),
                            self._spec_need_key(spec))
                    try:
                        spec._sweep_key_cache = mkey
                    except AttributeError:
                        pass
                if mkey is not None and mkey in misses:
                    continue          # proven unplaceable this sweep
            need = self._effective_need(spec)
            pool = (self._bundles[pg_key]["avail"] if pg_key is not None
                    else self.avail)
            charged = True
            if not fits(pool, need):
                # Saturated: the spec may still pipeline onto a BUSY
                # worker's existing grant (uncharged until the task
                # ahead of it completes) — reference worker-lease
                # pipelining. This is what keeps per-worker bursts >1
                # task deep, which the wire coalescing turns into
                # multi-spec TASK frames and paired TASK_DONEs.
                worker = self._pick_piggyback(spec, need, pg_key, refillable)
                if worker is None:
                    if mkey is not None:
                        misses.add(mkey)
                    continue
                charged = False
            else:
                worker = self._pick_worker(spec)
                if worker is None:
                    # no idle worker: pipeline onto a busy one rather
                    # than stalling the sweep on a spawn round-trip;
                    # spawning still happens below when even piggyback
                    # has no room, growing the pool toward max_workers
                    worker = self._pick_piggyback(spec, need, pg_key, refillable)
                    if worker is not None:
                        charged = False
            if worker is None:
                blocked = sum(1 for r in self._workers.values()
                              if r.blocked_depth > 0
                              and r.state not in (DEAD, ACTOR))
                # The max_workers soft cap governs the REUSABLE task-worker
                # pool only. Workers pinned by live actors are dedicated
                # processes outside the cap (reference worker_pool.cc keeps
                # its soft limit for returnable workers; actor workers are
                # started on demand) — otherwise long-lived actors starve
                # task/actor dispatch permanently.
                pool_count = sum(1 for r in self._workers.values()
                                 if r.state not in (DEAD, ACTOR))
                # Spawn only for unmet demand: never more in-flight spawns
                # than pending work items (raylet WorkerPool prestart logic,
                # worker_pool.cc PrestartWorkers, is demand-capped the same
                # way).
                if (pool_count - blocked < self._max_workers
                        and self._spawning < min(len(self._pending), 4)):
                    spawn_err: Optional[BaseException] = None
                    self._send_dispatch_outbox(outbox)
                    self._cv.release()
                    try:
                        # container envs bind the worker at spawn time
                        self.spawn_worker(
                            getattr(spec, "runtime_env", None))
                    except Exception as e:
                        # e.g. container engine/image missing: fail THE
                        # TASK (like a worker-side env error) instead of
                        # letting the exception escape into whatever
                        # thread ran this sweep and retrying forever
                        spawn_err = e
                    finally:
                        self._cv.acquire()
                    if spawn_err is not None:
                        if (has_container(getattr(spec, "runtime_env",
                                                  None))
                                and id(spec) in self._queued_at):
                            # env-driven spawn error (engine/image
                            # missing): deterministic — fail the task
                            self._pending.remove(spec)
                            self._queued_at.pop(id(spec), None)
                            self._demand_sub(spec)
                            self._cv.release()
                            try:
                                self._rt.on_unplaceable(
                                    spec, f"worker spawn failed: "
                                          f"{spawn_err}")
                            finally:
                                self._cv.acquire()
                        else:
                            # transient fork/exec failure: leave the
                            # spec queued; the 20 Hz backstop retries
                            sys.stderr.write(
                                f"ray_tpu: worker spawn failed "
                                f"({spawn_err}); will retry\n")
                break                 # no free worker: stop the sweep
            self._pending.remove(spec)
            t_enq = self._queued_at.pop(id(spec), None)
            if t_enq is not None:
                # metrics plane (r11): queue-wait phase from the stamp
                # the queue already keeps — enqueue pays nothing, and
                # the gate short-circuits with RAY_TPU_METRICS=0
                _mp.observe_queue_wait(time.monotonic() - t_enq,
                                       self.node_id)
            self._demand_sub(spec)
            if charged:
                acquire(pool, need)
            if not worker.container:     # image-bound hash is immutable
                worker.env_hash = self._spec_env_hash(spec)
            if isinstance(spec, ActorSpec):
                worker.acquired = need
                worker.pg_key = pg_key
                worker.state = ACTOR
                worker.actor_id = spec.actor_id
                self._rt.on_actor_dispatched(spec, worker.worker_id)
                outbox.append((worker.conn,
                               {"type": protocol.ACTOR_CREATE,
                                "spec": spec}))
            else:
                worker.state = BUSY
                worker.tasks[spec.task_id] = spec
                worker.task_res[spec.task_id] = (need, pg_key, charged)
                self._rt.on_task_dispatched(spec, worker.worker_id)
                msg = {"type": protocol.TASK, "spec": spec}
                # getattr: a spec pickled by a pre-r9 peer has no
                # trace fields (dataclasses pickle via __dict__)
                if _tp.enabled() and getattr(spec, "trace_id", 0):
                    self._record_dispatch_spans(spec, worker, t_enq,
                                                charged, msg)
                outbox.append((worker.conn, msg))
            dispatched += 1
        self._send_dispatch_outbox(
            outbox, eager=(len(outbox) == 1 and not self._pending))
        return dispatched > 0

    def _record_dispatch_spans(self, spec, worker: WorkerRec,
                               t_enq: Optional[float],
                               charged: bool, msg: dict) -> None:
        """Tracing plane (r9): the scheduler's two spans for a traced
        task — "queue" (enqueue → this sweep, derived from the
        _queued_at timestamp the queue already keeps, so enqueue pays
        nothing) and "lease" (the dispatch decision; charged=False
        marks a pipelined ride on a BUSY worker's grant). The TASK
        message carries (trace_id, lease span) so the worker's recv/
        exec spans chain under it across the process boundary."""
        t_now = _tp.now()
        t0 = int(t_enq * 1e9) if t_enq is not None else t_now
        sid_q = _tp.new_id()
        _tp.record("sched", "queue", t0, t_now, spec.trace_id, sid_q,
                   getattr(spec, "parent_span", 0),
                   {"node": self.node_id})
        sid_d = _tp.new_id()
        _tp.record("sched", "lease", t_now, _tp.now(), spec.trace_id,
                   sid_d, sid_q,
                   {"worker": worker.worker_id, "charged": charged})
        msg["_trace"] = (spec.trace_id, sid_d)

    def _fail_if_pg_removed(self, spec) -> None:
        """A queued spec whose placement group was removed can never run;
        surface the error instead of parking it forever. Called with the
        node lock held; the lock is DROPPED around the cluster query and
        the runtime callback (cluster holds its lock while taking node
        locks in scheduler_for_worker, so calling into it lock-held is an
        ABBA deadlock)."""
        if self._cluster is None:
            return
        pg_id = spec.placement_group_id
        self._cv.release()
        try:
            pg = self._cluster.get_pg(pg_id)
            removed = pg is None or pg.state == "REMOVED"
        finally:
            self._cv.acquire()
        if not removed or id(spec) not in self._queued_at:
            return
        self._pending.remove(spec)
        self._queued_at.pop(id(spec), None)
        self._demand_sub(spec)
        reason = (f"placement group {pg_id} was removed before "
                  f"{getattr(spec, 'name', spec)!r} could be scheduled")
        self._cv.release()
        try:
            self._rt.on_unplaceable(spec, reason)
        finally:
            self._cv.acquire()

    # ---- actor task routing (bypasses the queue: direct to its worker) ----
    def send_actor_task(self, actor_worker_id: str,
                        spec: ActorTaskSpec) -> bool:
        with self._lock:
            rec = self._workers.get(actor_worker_id)
            if rec is None or rec.state == DEAD or rec.conn is None:
                return False
            msg = {"type": protocol.ACTOR_TASK, "spec": spec}
            if _tp.enabled() and getattr(spec, "trace_id", 0):
                # actor tasks skip the queue: one "lease" span, no
                # queue span (there is no queueing head-side)
                sid = _tp.new_id()
                t0 = _tp.now()
                _tp.record("sched", "lease", t0, t0, spec.trace_id,
                           sid, getattr(spec, "parent_span", 0),
                           {"worker": actor_worker_id})
                msg["_trace"] = (spec.trace_id, sid)
            try:
                rec.conn.send(msg)
                return True
            except protocol.ConnectionClosed:
                return False

    def worker_for_actor(self, actor_id: str) -> Optional[str]:
        with self._lock:
            for rec in self._workers.values():
                if rec.actor_id == actor_id and rec.state != DEAD:
                    return rec.worker_id
        return None

    def worker_conns(self) -> list[tuple]:
        """(worker_id, connection) for every live registered worker —
        the tracing plane's TRACE_DUMP fan-out reads recorders over
        these (head- and agent-side alike)."""
        with self._lock:
            return [(r.worker_id, r.conn)
                    for r in self._workers.values()
                    if r.conn is not None and r.state != DEAD]

    # ---- introspection ----
    def stats(self) -> dict:
        with self._lock:
            return {
                "node_id": self.node_id,
                "total_resources": dict(self.total),
                "available_resources": dict(self.avail),
                "num_workers": self._alive_count(),
                "num_pending_tasks": len(self._pending),
                "workers": {
                    w: {"state": r.state, "actor_id": r.actor_id,
                        "blocked": r.blocked_depth}
                    for w, r in self._workers.items() if r.state != DEAD},
            }

    def shutdown(self) -> None:
        with self._cv:
            self._running = False
            workers = list(self._workers.values())
            self._cv.notify_all()
        for rec in workers:
            if rec.conn is not None:
                try:
                    rec.conn.send({"type": protocol.SHUTDOWN})
                except Exception:
                    pass
        deadline = time.time() + 3.0
        for rec in workers:
            if rec.proc is not None:
                try:
                    rec.proc.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    rec.proc.kill()

    # ---- node-death paths (ClusterTaskManager hooks) ----
    def die_silently(self) -> None:
        """Simulated abrupt node failure: SIGKILL every worker, stop the
        dispatch loop (and with it the heartbeat) WITHOUT telling anyone.
        The cluster health monitor must detect the death."""
        with self._cv:
            self._running = False
            workers = list(self._workers.values())
            self._cv.notify_all()
        for rec in workers:
            if rec.proc is not None:
                try:
                    rec.proc.kill()
                except Exception:
                    pass
            if rec.conn is not None:
                # Detach the connection so worker-lost callbacks don't fire
                # per-worker; recovery happens in one pass at node death.
                rec.conn.meta.pop("worker_id", None)
                try:
                    rec.conn.close()
                except Exception:
                    pass

    def reset_for_fence(self) -> None:
        """Fenced-node reset (r17): the head declared this node dead
        while it was still alive (partition / stalled link / long
        pause) and has re-placed everything it owed — finishing the
        local work would double-execute it. SIGKILL every worker, drop
        the queue and every ledger, restore full availability. Unlike
        ``die_silently`` the dispatch loop keeps running: the agent
        re-registers fresh and earns NEW work on clean workers."""
        with self._cv:
            workers = list(self._workers.values())
            self._workers.clear()
            self._spawning = 0
            self._pending.clear()
            self._queued_at.clear()
            self._pending_demand.clear()
            self._bundles.clear()
            self.avail = dict(self.total)
            self._cv.notify_all()
        doomed_oids: list = []
        for rec in workers:
            for task in rec.tasks.values():
                doomed_oids.extend(getattr(task, "return_ids", ()))
            if rec.conn is not None:
                # detach so per-worker lost callbacks don't fire and
                # re-report tasks the head already re-placed: this
                # reset IS the recovery
                rec.conn.meta.pop("worker_id", None)
                try:
                    rec.conn.close()
                except Exception:
                    pass
            if rec.proc is not None:
                try:
                    rec.proc.kill()
                except Exception:
                    pass
        # killed workers may have sealed result shm without delivering
        # TASK_DONE — reap locally (the same hygiene the worker-lost
        # path applies; shm outlives processes until reboot otherwise)
        from ray_tpu._private.object_store import reap_object_segments
        for oid in doomed_oids:
            try:
                reap_object_segments(oid)
            except Exception:
                pass

    def drain_for_death(self):
        """Collect (queued specs, running tasks, actor ids on this node)
        and tear everything down. Called by the cluster after the node is
        marked dead."""
        with self._cv:
            self._running = False
            queued = list(self._pending)
            self._pending.clear()
            self._queued_at.clear()
            workers = list(self._workers.values())
            self._cv.notify_all()
        running_tasks, actor_ids = [], []
        for rec in workers:
            if rec.state == DEAD:
                continue
            running_tasks.extend(t for t in rec.tasks.values()
                                 if isinstance(t, TaskSpec))
            if rec.actor_id is not None:
                actor_ids.append(rec.actor_id)
            rec.state = DEAD
            if rec.conn is not None:
                rec.conn.meta.pop("worker_id", None)
                try:
                    rec.conn.close()
                except Exception:
                    pass
            if rec.proc is not None:
                try:
                    rec.proc.kill()
                except Exception:
                    pass
        return queued, running_tasks, actor_ids
