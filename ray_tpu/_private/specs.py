"""Task / actor specifications — the wire-level unit of work.

Parity: reference ``TaskSpecification`` (src/ray/common/task/task_spec.h)
collapsed to the fields the centralized runtime needs. Functions and actor
classes are registered once in the controller's function store (reference
GcsFunctionManager, src/ray/gcs/gcs_server/gcs_kv_manager.h) and referenced
by content hash, so hot-loop task submission ships ids, not pickles.
"""
from __future__ import annotations

import hashlib
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

import cloudpickle


# Per-process-tree session tag, hex-only (id parsing splits on 'r').
# Prefixing every task/object id with it names shm segments
# rtpu_<tag>... so end-of-session orphan sweeps can't touch a
# concurrent driver's segments. Child processes inherit it via env.
import os as _os

import re as _re

_env_tag = _os.environ.get("RAY_TPU_SESSION", "")
# only a sane hex tag counts as inherited (ids are parsed on 'r' and
# segment names are swept by prefix — junk/empty values are ignored)
SESSION_TAG_INHERITED = bool(_re.fullmatch(r"[0-9a-f]{4,16}", _env_tag))
SESSION_TAG = _env_tag if SESSION_TAG_INHERITED else uuid.uuid4().hex[:6]
_os.environ["RAY_TPU_SESSION"] = SESSION_TAG


# Pooled entropy for hot-path id minting: uuid4() costs one urandom
# syscall per id (~0.4 ms under load on the CI box — 23% of per-submit
# head CPU); drawing a 1 KiB urandom block and slicing it keeps the
# same entropy per id at one syscall per ~85 ids.
import threading as _threading

_hex_pool = ""
_hex_lock = _threading.Lock()


def _reset_hex_pool() -> None:
    # fork safety: a child inheriting the pool (and possibly a held
    # lock) would mint the same ids as its parent — uuid4's per-call
    # urandom read never had that problem
    global _hex_pool, _hex_lock
    _hex_pool = ""
    _hex_lock = _threading.Lock()


if hasattr(_os, "register_at_fork"):
    _os.register_at_fork(after_in_child=_reset_hex_pool)


def rand_hex(n: int) -> str:
    global _hex_pool
    with _hex_lock:
        if len(_hex_pool) < n:
            _hex_pool = _os.urandom(512).hex()
        out, _hex_pool = _hex_pool[:n], _hex_pool[n:]
    return out


def new_task_id() -> str:
    return SESSION_TAG + rand_hex(12)


def new_actor_id() -> str:
    return rand_hex(16)


def function_id(pickled: bytes) -> str:
    return hashlib.sha1(pickled).hexdigest()[:16]


@dataclass
class RefMarker:
    """Placeholder for a top-level ObjectRef argument: the executing worker
    fetches the value before invoking the function (dependency resolution,
    reference transport/dependency_resolver.cc analogue)."""
    object_id: str


@dataclass
class TaskSpec:
    task_id: str
    func_id: str                      # key into the function store
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    return_ids: list[str] = field(default_factory=list)
    resources: dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    retries_used: int = 0
    name: str = ""
    # scheduling
    placement_group_id: Optional[str] = None
    placement_group_bundle_index: int = -1
    scheduling_strategy: Any = None
    node_id: Optional[str] = None     # node affinity (cluster sim)
    affinity_soft: bool = False       # soft affinity falls back anywhere
    # normalized (hard, soft) node-label constraints, or None
    label_constraints: Any = None
    runtime_env: Optional[dict] = None
    # bookkeeping (filled by runtime)
    pinned_refs: list[str] = field(default_factory=list)
    # tracing plane (r9): the trace this task belongs to and the span
    # it parents under (the submit span); 0 = untraced. Travels with
    # the pickled spec so scheduler/worker spans stitch cross-process.
    trace_id: int = 0
    parent_span: int = 0
    # Partition-tolerant membership (r17): bumped at every re-place
    # (retry, node-death resubmit, lease reclaim, lineage/head-restart
    # resubmission). Completion entries echo the attempt they executed;
    # the head drops terminal events for stale attempts (first-
    # terminal-wins), so a fenced zombie's TASK_DONE can never race
    # the re-placed winner into a double count.
    attempt: int = 0

    def __getstate__(self):
        # The metrics plane's head-side submit stamp (_submit_mono) is
        # read off the head's mirrored spec only — a monotonic reading
        # is meaningless in another process, so keep it off the wire.
        state = self.__dict__
        if "_submit_mono" in state:
            state = {k: v for k, v in state.items()
                     if k != "_submit_mono"}
        return state


@dataclass
class ActorSpec:
    actor_id: str
    class_id: str                     # key into the function store
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    resources: dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    name: Optional[str] = None
    namespace: str = "default"
    lifetime: Optional[str] = None    # "detached" or None
    placement_group_id: Optional[str] = None
    placement_group_bundle_index: int = -1
    scheduling_strategy: Any = None
    node_id: Optional[str] = None
    affinity_soft: bool = False
    label_constraints: Any = None
    runtime_env: Optional[dict] = None


@dataclass
class ActorTaskSpec:
    task_id: str
    actor_id: str
    method_name: str
    args: tuple = ()
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    return_ids: list[str] = field(default_factory=list)
    max_retries: int = 0              # from actor's max_task_retries
    retries_used: int = 0
    name: str = ""
    pinned_refs: list[str] = field(default_factory=list)
    # tracing plane (r9): see TaskSpec
    trace_id: int = 0
    parent_span: int = 0

    # same contract as TaskSpec: the head-side e2e submit stamp never
    # ships in pickled copies
    __getstate__ = TaskSpec.__getstate__


def bump_attempt(spec: Any) -> None:
    """Advance a spec's re-place attempt counter (r17 membership):
    call at EVERY site that hands an already-submitted spec back to
    ``cluster.submit``. Safe on pre-r17 pickled specs (the attribute
    is created) and on frozen/odd spec objects (best effort)."""
    try:
        spec.attempt = int(getattr(spec, "attempt", 0)) + 1
    except Exception:
        pass


def pickle_callable(fn: Any) -> tuple[str, bytes]:
    data = cloudpickle.dumps(fn)
    return function_id(data), data


def extract_ref_args(args: tuple, kwargs: dict):
    """Replace top-level ObjectRef args with RefMarkers; return pinned ids.

    Nested refs (inside lists/dicts/dataclasses) pass through pickled and
    arrive as borrowed ObjectRefs, matching reference semantics where only
    top-level refs are resolved to values before execution."""
    from ray_tpu._private.refs import ObjectRef
    pinned: list[str] = []

    def conv(v):
        if isinstance(v, ObjectRef):
            pinned.append(v.object_id)
            return RefMarker(v.object_id)
        return v

    new_args = tuple(conv(a) for a in args)
    new_kwargs = {k: conv(v) for k, v in kwargs.items()}
    return new_args, new_kwargs, pinned
