"""Controller: the centralized control plane (GCS equivalent).

Parity map to the reference GCS (src/ray/gcs/gcs_server/gcs_server.h:221-295):
- KV / function store   -> GcsInternalKVManager / GcsFunctionManager
- actor directory       -> GcsActorManager (incl. max_restarts bookkeeping,
                           gcs_actor_manager.h:89-91)
- named actors          -> GcsActorManager named-actor index
- placement groups      -> GcsPlacementGroupManager (bundle reservation)
- node table            -> GcsNodeManager
- task events           -> GcsTaskManager (bounded in-memory history)
- refcounts             -> centralized stand-in for the distributed
                           reference counter (core_worker/reference_count.cc)

All state is in-memory in the driver process; the multi-node story keeps
this process as head node (the reference's head-node GCS is the same
topology). Head fault tolerance: ``snapshot_state()`` serializes every
table and ``restore_state()`` rehydrates a restarted head from it
(reference gcs/gcs_server/gcs_init_data.cc loading from
gcs/store_client/redis_store_client.h storage).
"""
from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu._private.specs import ActorSpec

# Actor lifecycle states (reference rpc::ActorTableData states).
PENDING = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = PENDING
    worker_id: Optional[str] = None
    node_id: Optional[str] = None
    num_restarts: int = 0
    death_cause: str = ""
    created_at: float = field(default_factory=time.time)


@dataclass
class NodeTableRecord:
    """GcsNodeManager node-table entry (gcs_node_manager.h:62)."""
    node_id: str
    resources: dict
    is_head: bool = False
    alive: bool = True
    death_cause: str = ""
    labels: dict = field(default_factory=dict)
    registered_at: float = field(default_factory=time.time)
    # last per-node reporter sample (load, memory, worker RSS) carried
    # on heartbeats — reference dashboard/modules/reporter agent
    host_stats: dict = field(default_factory=dict)


class Controller:
    def __init__(self, task_event_capacity: Optional[int] = None):
        if task_event_capacity is None:
            from ray_tpu._private.config import CONFIG as _CFG
            task_event_capacity = _CFG.task_event_history
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock("controller", reentrant=True)
        self._kv: dict[tuple[str, str], Any] = {}
        self._actors: dict[str, ActorRecord] = {}
        self._named_actors: dict[tuple[str, str], str] = {}
        self._refcounts: dict[str, int] = {}
        self._pins: dict[str, int] = collections.defaultdict(int)
        self._pgs: dict[str, dict] = {}
        self._nodes: dict[str, NodeTableRecord] = {}
        # Cluster object directory: object_id -> {node_id} holding a
        # copy (reference ownership_based_object_directory.cc role; the
        # head IS the owner of record for every object). Extracted to
        # its own subsystem so getters, the scheduler locality hint,
        # and the broadcast coordinator share one location service.
        from ray_tpu._private.object_directory import ObjectDirectory
        self.directory = ObjectDirectory()
        # Lineage: return object_id -> producing TaskSpec, kept while
        # the object is referenced so a lost copy can be re-executed
        # (reference task_manager.h:269 ResubmitTask,
        # object_recovery_manager.h:41).
        self._lineage: dict[str, Any] = {}
        # Nested-ref ownership (reference reference_count.cc contained
        # refs): enclosing object id -> inner object ids it holds a
        # count on; released when the enclosing object is deleted.
        self._contained: dict[str, list[str]] = {}
        self._task_events: collections.deque = collections.deque(
            maxlen=task_event_capacity)
        from ray_tpu._private.pubsub import Publisher
        self.pubsub = Publisher()
        self._job_start = time.time()

    # ---- KV (GcsInternalKVManager parity) ----
    def kv_put(self, key: str, value: Any, namespace: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            k = (namespace, key)
            if not overwrite and k in self._kv:
                return False
            self._kv[k] = value
            return True

    def kv_get(self, key: str, namespace: str = "default") -> Any:
        with self._lock:
            return self._kv.get((namespace, key))

    def kv_del(self, key: str, namespace: str = "default") -> bool:
        with self._lock:
            return self._kv.pop((namespace, key), None) is not None

    def kv_exists(self, key: str, namespace: str = "default") -> bool:
        with self._lock:
            return (namespace, key) in self._kv

    def kv_keys(self, prefix: str = "", namespace: str = "default") -> list[str]:
        with self._lock:
            return [k for (ns, k) in self._kv
                    if ns == namespace and k.startswith(prefix)]

    # ---- function store ----
    def put_function(self, func_id: str, data: bytes) -> None:
        self.kv_put(func_id, data, namespace="_functions", overwrite=False)

    def get_function(self, func_id: str) -> Optional[bytes]:
        return self.kv_get(func_id, namespace="_functions")

    # ---- refcounts ----
    def addref(self, object_id: str, n: int = 1) -> None:
        with self._lock:
            self._refcounts[object_id] = self._refcounts.get(object_id, 0) + n

    def decref(self, object_id: str) -> bool:
        """Returns True when the object is now unreferenced and unpinned."""
        with self._lock:
            c = self._refcounts.get(object_id, 0) - 1
            if c > 0:
                self._refcounts[object_id] = c
                return False
            self._refcounts.pop(object_id, None)
            return self._pins[object_id] == 0

    def pin(self, object_id: str) -> None:
        with self._lock:
            self._pins[object_id] += 1

    def unpin(self, object_id: str) -> bool:
        """Returns True when the object is now unreferenced and unpinned."""
        with self._lock:
            self._pins[object_id] = max(0, self._pins[object_id] - 1)
            return (self._pins[object_id] == 0
                    and self._refcounts.get(object_id, 0) == 0)

    def refcount(self, object_id: str) -> int:
        with self._lock:
            return self._refcounts.get(object_id, 0)

    def pinned_ids(self) -> list[str]:
        """Objects pinned by in-flight work — the store's spill policy
        must not touch these (they may be mid-transfer as task args)."""
        with self._lock:
            return [oid for oid, n in self._pins.items() if n > 0]

    def unreferenced(self, object_id: str) -> bool:
        with self._lock:
            return (self._refcounts.get(object_id, 0) == 0
                    and self._pins[object_id] == 0)

    # ---- object directory (delegates to the ObjectDirectory
    # subsystem; these remain the control-plane entry points) ----
    def add_location(self, object_id: str, node_id: str,
                     nbytes: int = 0, partial: bool = False) -> None:
        self.directory.add(object_id, node_id, nbytes, partial=partial)

    def remove_location(self, object_id: str,
                        node_id: Optional[str] = None) -> None:
        self.directory.remove(object_id, node_id)

    def locations(self, object_id: str) -> list[str]:
        return self.directory.locations(object_id)

    def has_location(self, object_id: str) -> bool:
        return self.directory.has(object_id)

    def purge_node_locations(self, node_id: str) -> list[str]:
        """Drop `node_id` from every directory entry; returns object ids
        that now have NO copy anywhere (lineage-recovery candidates)."""
        return self.directory.purge_node(node_id)

    # ---- nested-ref ownership ----
    def register_contained(self, object_id: str,
                           ids: list[str]) -> list[str]:
        """The sealed object `object_id` pickled refs to `ids` inside
        it: hold a count on each until it is deleted. A reseal with
        DIFFERENT contents (lineage resubmission creates fresh inner
        ids) refreshes the registration; the previously-held ids are
        RETURNED and the caller must decref them through the full
        deletion path."""
        new = list(ids)
        with self._lock:
            old = self._contained.get(object_id)
            if old == new or (old is None and not new):
                return []
            if new:
                self._contained[object_id] = new
                for cid in new:
                    self._refcounts[cid] = self._refcounts.get(cid, 0) + 1
            else:
                self._contained.pop(object_id, None)
            return list(old or ())

    def pop_contained(self, object_id: str) -> list[str]:
        with self._lock:
            return self._contained.pop(object_id, [])

    # ---- lineage (ResubmitTask parity) ----
    def record_lineage(self, spec: Any) -> None:
        with self._lock:
            for oid in getattr(spec, "return_ids", ()):
                self._lineage[oid] = spec

    def lineage_for(self, object_id: str) -> Any:
        with self._lock:
            return self._lineage.get(object_id)

    def drop_lineage(self, object_id: str) -> None:
        with self._lock:
            self._lineage.pop(object_id, None)

    # ---- actors ----
    def register_actor(self, spec: ActorSpec) -> ActorRecord:
        with self._lock:
            if spec.name is not None:
                key = (spec.namespace, spec.name)
                if key in self._named_actors:
                    raise ValueError(
                        f"Actor name {spec.name!r} already taken in "
                        f"namespace {spec.namespace!r}")
                self._named_actors[key] = spec.actor_id
            rec = ActorRecord(spec=spec)
            self._actors[spec.actor_id] = rec
            return rec

    def get_actor(self, actor_id: str) -> Optional[ActorRecord]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str,
                        namespace: str = "default") -> Optional[str]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def set_actor_state(self, actor_id: str, state: str,
                        worker_id: Optional[str] = None,
                        death_cause: str = "",
                        node_id: Optional[str] = None) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            rec.state = state
            if worker_id is not None:
                rec.worker_id = worker_id
            if node_id is not None:
                rec.node_id = node_id
            if death_cause:
                rec.death_cause = death_cause
            if state == DEAD and rec.spec.name is not None:
                self._named_actors.pop(
                    (rec.spec.namespace, rec.spec.name), None)
        from ray_tpu._private.pubsub import ACTOR_CHANNEL
        self.pubsub.publish(ACTOR_CHANNEL, {
            "actor_id": actor_id, "state": state,
            "death_cause": death_cause})

    def list_actors(self) -> list[dict]:
        with self._lock:
            return [{
                "actor_id": aid, "state": r.state, "name": r.spec.name,
                "class_id": r.spec.class_id, "worker_id": r.worker_id,
                "num_restarts": r.num_restarts,
                "max_restarts": r.spec.max_restarts,
                "death_cause": r.death_cause,
            } for aid, r in self._actors.items()]

    # ---- placement groups (view pushed by the ClusterTaskManager) ----
    def register_pg_view(self, entry: dict) -> None:
        with self._lock:
            self._pgs[entry["placement_group_id"]] = dict(entry)

    def list_pgs(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._pgs.values()]

    # ---- node table (GcsNodeManager parity) ----
    def publish_node_event(self, node_id: str, state: str,
                           cause: str = "") -> None:
        from ray_tpu._private.pubsub import NODE_CHANNEL
        self.pubsub.publish(NODE_CHANNEL, {
            "node_id": node_id, "state": state, "cause": cause})

    def register_node(self, node_id: str, resources: dict,
                      is_head: bool = False,
                      labels: Optional[dict] = None) -> None:
        with self._lock:
            self._nodes[node_id] = NodeTableRecord(
                node_id=node_id, resources=dict(resources),
                is_head=is_head, labels=dict(labels or {}))

    def set_node_state(self, node_id: str, alive: bool,
                       cause: str = "") -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is not None:
                rec.alive = alive
                if cause:
                    rec.death_cause = cause

    def update_host_stats(self, node_id: str, stats: dict) -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is not None:
                rec.host_stats = dict(stats)

    def list_nodes(self) -> list[dict]:
        with self._lock:
            return [{
                "node_id": r.node_id, "alive": r.alive,
                "is_head": r.is_head, "resources": dict(r.resources),
                "death_cause": r.death_cause, "labels": dict(r.labels),
                "host_stats": dict(r.host_stats),
            } for r in self._nodes.values()]

    def actors_on_node(self, node_id: str) -> list[str]:
        """Non-dead actors whose last known placement is `node_id`."""
        with self._lock:
            return [aid for aid, r in self._actors.items()
                    if r.node_id == node_id and r.state != DEAD]

    # ---- persistence (GCS storage parity) ----
    _SNAPSHOT_TABLES = ("_kv", "_actors", "_named_actors", "_refcounts",
                        "_pins", "_pgs", "_nodes", "_lineage",
                        "_contained")

    def snapshot_state(self) -> bytes:
        """Snapshot every table into one blob (reference GCS tables are
        flushed to the storage backend). Only the shallow table copies
        happen under the lock; the pickle — the expensive part — runs
        outside so the periodic snapshot never stalls the control
        plane."""
        import pickle

        import cloudpickle
        with self._lock:
            state = {name: dict(getattr(self, name))
                     for name in self._SNAPSHOT_TABLES}
            state["_task_events"] = list(self._task_events)
        # the directory snapshots under its own lock (its table keys
        # keep the pre-extraction names for blob continuity)
        (state["_locations"],
         state["_location_nbytes"]) = self.directory.snapshot()
        # cloudpickle, not stdlib pickle: lineage/KV hold raw user task
        # args (lambdas, closures) that the wire layer supports — a
        # snapshot that crashes on them silently disables head FT
        return cloudpickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)

    def restore_state(self, blob: bytes) -> None:
        """Rehydrate from a snapshot (reference gcs_init_data.cc). Node
        records for OLD head processes are dropped — the restarted head
        registers itself fresh; agent records are kept so the cluster
        can await their re-registration."""
        import pickle
        state = pickle.loads(blob)
        with self._lock:
            current = dict(self._nodes)          # the new head's record(s)
            for name in self._SNAPSHOT_TABLES:
                setattr(self, name, state.get(name, {}))
            self._pins = collections.defaultdict(
                int, state["_pins"])             # keep defaulting behavior
            self._nodes = {nid: r for nid, r in self._nodes.items()
                           if not r.is_head}
            self._nodes.update(current)
            self._task_events.extend(state.get("_task_events", ()))
        self.directory.restore(state.get("_locations", {}),
                               state.get("_location_nbytes", {}))

    # ---- task events (GcsTaskManager parity) ----
    def record_task_event(self, task_id: str, name: str, state: str,
                          worker_id: str = "", error: str = "") -> None:
        with self._lock:
            self._task_events.append({
                "task_id": task_id, "name": name, "state": state,
                "worker_id": worker_id, "error": error, "ts": time.time(),
            })

    def record_task_events(self, events: list[dict]) -> None:
        """Batched ingest from worker-side event buffers (reference
        GcsTaskManager AddTaskEventData): events carry their own
        worker-side ts/duration_s."""
        with self._lock:
            self._task_events.extend(events)

    def list_task_events(self, limit: int = 1000) -> list[dict]:
        with self._lock:
            out = list(self._task_events)
        return out[-limit:]

    def summarize_tasks(self) -> dict:
        with self._lock:
            latest: dict[str, dict] = {}
            for ev in self._task_events:
                latest[ev["task_id"]] = ev
        counts: dict[str, int] = collections.defaultdict(int)
        for ev in latest.values():
            counts[ev["state"]] += 1
        return dict(counts)
