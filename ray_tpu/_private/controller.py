"""Controller: the centralized control plane (GCS equivalent).

Parity map to the reference GCS (src/ray/gcs/gcs_server/gcs_server.h:221-295):
- KV / function store   -> GcsInternalKVManager / GcsFunctionManager
- actor directory       -> GcsActorManager (incl. max_restarts bookkeeping,
                           gcs_actor_manager.h:89-91)
- named actors          -> GcsActorManager named-actor index
- placement groups      -> GcsPlacementGroupManager (bundle reservation)
- node table            -> GcsNodeManager
- task events           -> GcsTaskManager (bounded in-memory history)
- refcounts             -> centralized stand-in for the distributed
                           reference counter (core_worker/reference_count.cc)

All state is in-memory in the driver process; the multi-node story keeps
this process as head node (the reference's head-node GCS is the same
topology). Head fault tolerance: ``snapshot_state()`` serializes every
table and ``restore_state()`` rehydrates a restarted head from it
(reference gcs/gcs_server/gcs_init_data.cc loading from
gcs/store_client/redis_store_client.h storage).

r16 hot-table striping: the three tables every submit/done/decref
touches — the ref/pin table, the live-task spec mirror (+ lineage),
and the object directory — no longer live under ``controller._lock``.
They are striped shards with per-shard plain locks (striped.py), so
the driver submit thread, the poller's completion handling, and the
decref flusher stop convoying through one reentrant lock at 100k-task
scale. ``_lock`` still guards the cold tables (KV, actors, nodes,
PGs, task events). WAL composition: sharded mutations complete BEFORE
their record is appended, and ``snapshot_state`` captures the WAL
frontier BEFORE capturing any sharded table — see striped.py for why
that preserves the r15 exact-frontier recovery invariant.
"""
from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu._private import striped
from ray_tpu._private.head_ha import TERMINAL_TASK_STATES
from ray_tpu._private.specs import ActorSpec

# Actor lifecycle states (reference rpc::ActorTableData states).
PENDING = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = PENDING
    worker_id: Optional[str] = None
    node_id: Optional[str] = None
    num_restarts: int = 0
    death_cause: str = ""
    created_at: float = field(default_factory=time.time)


@dataclass
class NodeTableRecord:
    """GcsNodeManager node-table entry (gcs_node_manager.h:62)."""
    node_id: str
    resources: dict
    is_head: bool = False
    alive: bool = True
    death_cause: str = ""
    labels: dict = field(default_factory=dict)
    registered_at: float = field(default_factory=time.time)
    # last per-node reporter sample (load, memory, worker RSS) carried
    # on heartbeats — reference dashboard/modules/reporter agent
    host_stats: dict = field(default_factory=dict)


class Controller:
    def __init__(self, task_event_capacity: Optional[int] = None):
        from ray_tpu._private.config import CONFIG as _CFG
        if task_event_capacity is None:
            task_event_capacity = _CFG.task_event_history
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock("controller", reentrant=True)
        self._kv: dict[tuple[str, str], Any] = {}
        self._actors: dict[str, ActorRecord] = {}
        self._named_actors: dict[tuple[str, str], str] = {}
        # Striped ref/pin table (r16): one [refcount, pins] entry per
        # object id, per-stripe locks, entries evicted at zero/zero.
        # The WAL hook runs INSIDE the stripe lock (absolute values
        # must log in mutation order — striped.py docstring).
        self._refs = striped.RefTable(log=self._log_ref_locked)
        self._pgs: dict[str, dict] = {}
        self._nodes: dict[str, NodeTableRecord] = {}
        # Cluster object directory: object_id -> {node_id} holding a
        # copy (reference ownership_based_object_directory.cc role; the
        # head IS the owner of record for every object). Extracted to
        # its own subsystem so getters, the scheduler locality hint,
        # and the broadcast coordinator share one location service.
        from ray_tpu._private.object_directory import ObjectDirectory
        self.directory = ObjectDirectory()
        # Lineage: return object_id -> producing TaskSpec, kept while
        # the object is referenced so a lost copy can be re-executed
        # (reference task_manager.h:269 ResubmitTask,
        # object_recovery_manager.h:41). Striped + FIFO-bounded: it is
        # the one hot table with no natural terminal event while refs
        # stay live, so a 100k drain would otherwise keep 100k specs
        # resident. Evicting an old entry only disables lost-copy
        # reconstruction for that object (reference lineage eviction
        # under max_lineage_bytes degrades the same way).
        self._lineage = striped.StripedMap(
            max_entries=_CFG.head_lineage_max)
        # Nested-ref ownership (reference reference_count.cc contained
        # refs): enclosing object id -> inner object ids it holds a
        # count on; released when the enclosing object is deleted.
        self._contained: dict[str, list[str]] = {}
        self._task_events: collections.deque = collections.deque(
            maxlen=task_event_capacity)
        # Live plain-task table (r15 head HA): task_id -> spec for every
        # submitted-not-terminal driver task. This is what a restarted
        # head consults to decide which specs are still owed an
        # execution (mirrored-to-an-agent specs wait for the rejoin
        # reconcile; the rest re-place immediately). Striped (r16):
        # submit inserts and terminal pops ride per-shard locks.
        self._live_tasks = striped.StripedMap()
        # Batched decref-delta watermarks (r16): node_id -> highest
        # applied delta seq. The dedup that extends the r15 rejoin
        # replay rules to NODE_DECREF_DELTA frames: a replayed delta at
        # or below the watermark was already applied by this head (or
        # survives in the snapshot/WAL refs records) and is skipped.
        self._decref_seqs: dict[str, int] = {}
        # Node incarnations (r17 partition-tolerant membership):
        # node_id -> monotonic epoch, minted at every registration and
        # bumped at every death declaration. WAL-logged and
        # snapshotted, so incarnations stay monotonic across head
        # restarts — a zombie from before ANY restart still fences.
        # The head stamps the registration's incarnation on the
        # agent's connection; frames from a connection whose
        # incarnation trails this table are dropped and answered with
        # NODE_FENCED (reference: GCS rejects RPCs from de-registered
        # raylets the same way).
        self._incarnations: dict[str, int] = {}
        # Head-HA logger (r15): set by the runtime once recovery is
        # done; while None (or during replay) the _walog hooks no-op.
        self.ha = None
        from ray_tpu._private.pubsub import Publisher
        self.pubsub = Publisher()
        self._job_start = time.time()

    # ---- head-HA write-ahead logging (r15) ----
    def _walog(self, rtype: str, data: Any) -> None:
        """Append one WAL record. For ``_lock``-guarded tables this is
        called inside the locked region that performed the mutation
        (mutate+log atomic w.r.t. the frontier capture, which shares
        ``_lock``). For the striped tables the call site sequences the
        append AFTER the mutation instead; snapshot_state captures the
        frontier BEFORE the striped tables, which preserves the same
        replay invariant (striped.py docstring)."""
        ha = self.ha
        if ha is not None:
            ha.log(rtype, data)

    def _log_ref_locked(self, object_id: str, refcount: int,
                        pins: int) -> None:
        """RefTable WAL hook: absolute refcount+pin record (set
        semantics — replay-safe under duplication), coalesced WAL-side
        per flush window. Runs with the object's stripe lock held."""
        ha = self.ha
        if ha is not None:
            ha.log_ref(object_id, refcount, pins)

    # ---- KV (GcsInternalKVManager parity) ----
    def kv_put(self, key: str, value: Any, namespace: str = "default",
               overwrite: bool = True) -> bool:
        with self._lock:
            k = (namespace, key)
            if not overwrite and k in self._kv:
                return False
            self._kv[k] = value
            self._walog("kv", (namespace, key, value))
            return True

    def kv_get(self, key: str, namespace: str = "default") -> Any:
        with self._lock:
            return self._kv.get((namespace, key))

    def kv_del(self, key: str, namespace: str = "default") -> bool:
        with self._lock:
            hit = self._kv.pop((namespace, key), None) is not None
            if hit:
                self._walog("kv_del", (namespace, key))
            return hit

    def kv_exists(self, key: str, namespace: str = "default") -> bool:
        with self._lock:
            return (namespace, key) in self._kv

    def kv_keys(self, prefix: str = "", namespace: str = "default") -> list[str]:
        with self._lock:
            return [k for (ns, k) in self._kv
                    if ns == namespace and k.startswith(prefix)]

    # ---- function store ----
    def put_function(self, func_id: str, data: bytes) -> None:
        self.kv_put(func_id, data, namespace="_functions", overwrite=False)

    def get_function(self, func_id: str) -> Optional[bytes]:
        return self.kv_get(func_id, namespace="_functions")

    # ---- refcounts (striped RefTable; per-shard locks) ----
    def addref(self, object_id: str, n: int = 1) -> None:
        self._refs.addref(object_id, n)

    def decref(self, object_id: str) -> bool:
        """Returns True when the object is now unreferenced and unpinned."""
        return self._refs.decref(object_id)

    def apply_decref_delta(self, node_id: str, seq: int,
                           counts: dict) -> Optional[list[str]]:
        """Batched decref delta from a delegated agent (r16): apply
        ``{oid: n}`` per-shard and return the ids now deletable, or
        None when the delta is a replayed duplicate (its seq is at or
        below the node's watermark). The watermark advances — and WAL-
        logs — BEFORE the counts apply: a crash in between loses the
        releases (objects leak until shutdown, the safe direction)
        instead of double-applying them on replay (premature free)."""
        if seq:
            with self._lock:
                if seq <= self._decref_seqs.get(node_id, 0):
                    return None
                self._decref_seqs[node_id] = seq
                self._walog("dref_seq", (node_id, seq))
        return self._refs.apply_deltas(counts)

    def reset_decref_seq(self, node_id: str) -> None:
        """A FRESH (non-rejoin) agent registered under this node id:
        its delta counter restarts, so the watermark must too."""
        with self._lock:
            if self._decref_seqs.pop(node_id, None) is not None:
                self._walog("dref_seq", (node_id, 0))

    def pin(self, object_id: str) -> None:
        self._refs.pin(object_id)

    def unpin(self, object_id: str) -> bool:
        """Returns True when the object is now unreferenced and unpinned."""
        return self._refs.unpin(object_id)

    def refcount(self, object_id: str) -> int:
        return self._refs.refcount(object_id)

    def pinned_ids(self) -> list[str]:
        """Objects pinned by in-flight work — the store's spill policy
        must not touch these (they may be mid-transfer as task args)."""
        return self._refs.pinned_ids()

    def unreferenced(self, object_id: str) -> bool:
        return self._refs.unreferenced(object_id)

    def ref_tables(self) -> tuple[dict, dict]:
        """(refcounts, pins) merged one-dict views (tests, snapshots)."""
        return self._refs.snapshot()

    # ---- object directory (delegates to the ObjectDirectory
    # subsystem; these remain the control-plane entry points) ----
    def add_location(self, object_id: str, node_id: str,
                     nbytes: int = 0, partial: bool = False) -> None:
        self.directory.add(object_id, node_id, nbytes, partial=partial)
        if not partial:
            # partial holders (r12 cut-through) are advisory in-flight
            # state: meaningless to a restarted head, never logged
            self._walog("dir+", (object_id, node_id, nbytes))

    def remove_location(self, object_id: str,
                        node_id: Optional[str] = None) -> None:
        self.directory.remove(object_id, node_id)
        self._walog("dir-", (object_id, node_id))

    def locations(self, object_id: str) -> list[str]:
        return self.directory.locations(object_id)

    def has_location(self, object_id: str) -> bool:
        return self.directory.has(object_id)

    def purge_node_locations(self, node_id: str) -> list[str]:
        """Drop `node_id` from every directory entry; returns object ids
        that now have NO copy anywhere (lineage-recovery candidates)."""
        self._walog("dir_purge", node_id)
        return self.directory.purge_node(node_id)

    # ---- nested-ref ownership ----
    def register_contained(self, object_id: str,
                           ids: list[str]) -> list[str]:
        """The sealed object `object_id` pickled refs to `ids` inside
        it: hold a count on each until it is deleted. A reseal with
        DIFFERENT contents (lineage resubmission creates fresh inner
        ids) refreshes the registration; the previously-held ids are
        RETURNED and the caller must decref them through the full
        deletion path."""
        new = list(ids)
        with self._lock:
            old = self._contained.get(object_id)
            if old == new or (old is None and not new):
                return []
            # inner-ref counts FIRST (each logs its absolute value
            # inside its stripe lock, taken UNDER _lock — the
            # controller-lock -> stripe-lock order apply_decref_delta
            # also uses): a crash between these appends and the
            # contained record below leaks conservatively, while the
            # reverse order would let replay decref counts that were
            # never incremented — a premature free
            for cid in new:
                self._refs.addref(cid)
            if new:
                self._contained[object_id] = new
            else:
                self._contained.pop(object_id, None)
            self._walog("contained", (object_id, new))
            return list(old or ())

    def pop_contained(self, object_id: str) -> list[str]:
        with self._lock:
            out = self._contained.pop(object_id, [])
            if out:
                self._walog("contained", (object_id, []))
            return out

    # ---- lineage (ResubmitTask parity) ----
    def record_lineage(self, spec: Any) -> None:
        for oid in getattr(spec, "return_ids", ()):
            self._lineage.put(oid, spec)

    # ---- live-task accounting (r15 head HA) ----
    def task_submitted(self, spec: Any) -> None:
        """Record everything a restarted head needs to re-own this
        task: lineage for its return objects, the live-task entry that
        marks it submitted-not-terminal, and ONE WAL record carrying
        the spec (replay rebuilds both tables from it). Mutations
        complete before the record is appended — the striped-table WAL
        invariant (striped.py)."""
        for oid in getattr(spec, "return_ids", ()):
            self._lineage.put(oid, spec)
        tid = getattr(spec, "task_id", None)
        if tid is not None:
            self._live_tasks.put(tid, spec)
        self._walog("task", spec)

    def live_task(self, task_id: str) -> Any:
        return self._live_tasks.get(task_id)

    def live_task_ids(self) -> list[str]:
        return self._live_tasks.keys()

    def lineage_for(self, object_id: str) -> Any:
        return self._lineage.get(object_id)

    def drop_lineage(self, object_id: str) -> None:
        self._lineage.pop(object_id)

    # ---- actors ----
    def register_actor(self, spec: ActorSpec) -> ActorRecord:
        with self._lock:
            if spec.name is not None:
                key = (spec.namespace, spec.name)
                if key in self._named_actors:
                    raise ValueError(
                        f"Actor name {spec.name!r} already taken in "
                        f"namespace {spec.namespace!r}")
                self._named_actors[key] = spec.actor_id
            rec = ActorRecord(spec=spec)
            self._actors[spec.actor_id] = rec
            self._walog("actor", spec)
            return rec

    def get_actor(self, actor_id: str) -> Optional[ActorRecord]:
        with self._lock:
            return self._actors.get(actor_id)

    def get_named_actor(self, name: str,
                        namespace: str = "default") -> Optional[str]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def set_actor_state(self, actor_id: str, state: str,
                        worker_id: Optional[str] = None,
                        death_cause: str = "",
                        node_id: Optional[str] = None) -> None:
        with self._lock:
            rec = self._actors.get(actor_id)
            if rec is None:
                return
            rec.state = state
            if worker_id is not None:
                rec.worker_id = worker_id
            if node_id is not None:
                rec.node_id = node_id
            if death_cause:
                rec.death_cause = death_cause
            if state == DEAD and rec.spec.name is not None:
                self._named_actors.pop(
                    (rec.spec.namespace, rec.spec.name), None)
            self._walog("actor_state",
                        (actor_id, state, rec.worker_id, rec.node_id,
                         rec.death_cause, rec.num_restarts))
        from ray_tpu._private.pubsub import ACTOR_CHANNEL
        self.pubsub.publish(ACTOR_CHANNEL, {
            "actor_id": actor_id, "state": state,
            "death_cause": death_cause})

    def list_actors(self) -> list[dict]:
        with self._lock:
            return [{
                "actor_id": aid, "state": r.state, "name": r.spec.name,
                "class_id": r.spec.class_id, "worker_id": r.worker_id,
                "num_restarts": r.num_restarts,
                "max_restarts": r.spec.max_restarts,
                "death_cause": r.death_cause,
            } for aid, r in self._actors.items()]

    # ---- placement groups (view pushed by the ClusterTaskManager) ----
    def register_pg_view(self, entry: dict) -> None:
        with self._lock:
            self._pgs[entry["placement_group_id"]] = dict(entry)
            self._walog("pg", dict(entry))

    def list_pgs(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._pgs.values()]

    # ---- node table (GcsNodeManager parity) ----
    def publish_node_event(self, node_id: str, state: str,
                           cause: str = "") -> None:
        from ray_tpu._private.pubsub import NODE_CHANNEL
        self.pubsub.publish(NODE_CHANNEL, {
            "node_id": node_id, "state": state, "cause": cause})

    def register_node(self, node_id: str, resources: dict,
                      is_head: bool = False,
                      labels: Optional[dict] = None) -> None:
        with self._lock:
            self._nodes[node_id] = NodeTableRecord(
                node_id=node_id, resources=dict(resources),
                is_head=is_head, labels=dict(labels or {}))
            if not is_head:
                # head records are dropped at restore (the restarted
                # head registers itself fresh): never logged
                self._walog("node", (node_id, dict(resources),
                                     dict(labels or {})))

    def set_node_state(self, node_id: str, alive: bool,
                       cause: str = "") -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is not None:
                rec.alive = alive
                if cause:
                    rec.death_cause = cause
                if not rec.is_head:
                    self._walog("node_state", (node_id, alive, cause))

    # ---- node incarnations (r17) ----
    def mint_incarnation(self, node_id: str) -> int:
        """Next incarnation for a (re)registering node. Monotonic per
        node_id across head restarts (WAL-logged, snapshotted)."""
        with self._lock:
            inc = self._incarnations.get(node_id, 0) + 1
            self._incarnations[node_id] = inc
            self._walog("incarnation", (node_id, inc))
            return inc

    def bump_incarnation(self, node_id: str) -> int:
        """Invalidate the node's current incarnation (death
        declaration): any connection still carrying the old epoch is
        fenced from here on — the zombie window closes the moment the
        death recovery that re-places its work begins."""
        return self.mint_incarnation(node_id)

    def node_incarnation(self, node_id: str) -> Optional[int]:
        # LOCK-FREE by design: called on every state-bearing agent
        # frame (the fence admission check) — a GIL-atomic dict read
        # of an int that only ever rises. Worst case a frame racing a
        # death bump is admitted one beat early, which the death
        # recovery's mirror drain already tolerates; taking the global
        # controller lock here would re-serialize the hot dispatch
        # path the r16 striping work got off it.
        return self._incarnations.get(node_id)

    def incarnations(self) -> dict:
        with self._lock:
            return dict(self._incarnations)

    def update_host_stats(self, node_id: str, stats: dict) -> None:
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is not None:
                rec.host_stats = dict(stats)

    def list_nodes(self) -> list[dict]:
        with self._lock:
            return [{
                "node_id": r.node_id, "alive": r.alive,
                "is_head": r.is_head, "resources": dict(r.resources),
                "death_cause": r.death_cause, "labels": dict(r.labels),
                "host_stats": dict(r.host_stats),
            } for r in self._nodes.values()]

    def actors_on_node(self, node_id: str) -> list[str]:
        """Non-dead actors whose last known placement is `node_id`."""
        with self._lock:
            return [aid for aid, r in self._actors.items()
                    if r.node_id == node_id and r.state != DEAD]

    # ---- persistence (GCS storage parity) ----
    # Cold tables captured under _lock; the striped tables keep their
    # legacy blob keys but are captured shard-aware (after the
    # frontier) — the blob SHAPE is unchanged across r15 <-> r16.
    _SNAPSHOT_TABLES = ("_kv", "_actors", "_named_actors", "_pgs",
                        "_nodes", "_contained", "_decref_seqs",
                        "_incarnations")
    _STRIPED_TABLES = ("_refcounts", "_pins", "_lineage", "_live_tasks")

    def snapshot_state(self, extra_fn: Optional[Any] = None) -> bytes:
        """Snapshot every table into one blob (reference GCS tables are
        flushed to the storage backend). Only shallow table copies
        happen under locks; the pickle — the expensive part — runs
        outside. With the r15 WAL attached, the blob embeds the WAL
        sequence frontier it covers. Capture order is the r16
        invariant: frontier FIRST (under ``_lock``, atomic with the
        cold-table capture whose mutate+log pairs share that lock),
        striped tables and the directory AFTER — a record at or below
        the frontier is then provably visible in the captured shard
        (striped.py docstring). ``extra_fn`` supplies runtime-owned
        tables (per-node spec mirrors + lease ledgers) and likewise
        runs after the frontier capture: a mirror add logged at
        seq <= frontier is guaranteed visible in the captured mirror,
        while one logged later replays from the WAL."""
        import pickle

        import cloudpickle
        with self._lock:
            state = {name: dict(getattr(self, name))
                     for name in self._SNAPSHOT_TABLES}
            state["_task_events"] = list(self._task_events)
            if self.ha is not None:
                state["_wal_seq"] = self.ha.wal_seq()
        # striped tables: captured per-shard AFTER the frontier, merged
        # into the legacy one-dict blob keys
        (state["_refcounts"],
         state["_pins"]) = self._refs.snapshot()
        state["_lineage"] = self._lineage.snapshot()
        state["_live_tasks"] = self._live_tasks.snapshot()
        # the directory snapshots under its own stripe locks (its table
        # keys keep the pre-extraction names for blob continuity)
        (state["_locations"],
         state["_location_nbytes"]) = self.directory.snapshot()
        if extra_fn is not None:
            state.update(extra_fn())
        # cloudpickle, not stdlib pickle: lineage/KV hold raw user task
        # args (lambdas, closures) that the wire layer supports — a
        # snapshot that crashes on them silently disables head FT
        return cloudpickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)

    def restore_state(self, blob: bytes) -> dict:
        """Rehydrate from a snapshot (reference gcs_init_data.cc). Node
        records for OLD head processes are dropped — the restarted head
        registers itself fresh; agent records are kept so the cluster
        can await their re-registration. Returns the raw state dict so
        the runtime can pick up its own tables (mirrors, WAL
        frontier)."""
        import pickle
        state = pickle.loads(blob)
        with self._lock:
            current = dict(self._nodes)          # the new head's record(s)
            for name in self._SNAPSHOT_TABLES:
                # _decref_seqs rides this loop too (it is in
                # _SNAPSHOT_TABLES; r15-era blobs simply lack the key)
                setattr(self, name, state.get(name, {}))
            self._nodes = {nid: r for nid, r in self._nodes.items()
                           if not r.is_head}
            self._nodes.update(current)
            self._task_events.extend(state.get("_task_events", ()))
        self._refs.restore(state.get("_refcounts", {}),
                           state.get("_pins", {}))
        self._lineage.restore(state.get("_lineage", {}))
        self._live_tasks.restore(state.get("_live_tasks", {}))
        self.directory.restore(state.get("_locations", {}),
                               state.get("_location_nbytes", {}))
        return state

    def apply_wal_record(self, rtype: str, data: Any) -> None:
        """Replay one WAL record onto the tables (r15 recovery). Every
        branch is set-semantics: applying a record twice — the torn-
        compaction overlap, or a test replaying the tail again —
        converges to the same state. Striped-table branches go through
        the shard-aware entry points (r16)."""
        if rtype == "task":
            spec = data
            tid = getattr(spec, "task_id", None)
            if tid is not None:
                self._live_tasks.put(tid, spec)
            for oid in getattr(spec, "return_ids", ()):
                self._lineage.put(oid, spec)
        elif rtype == "task_done":
            self._live_tasks.pop(data)
        elif rtype == "refs":
            for oid, (ref, pin) in data.items():
                self._refs.set_absolute(oid, ref, pin)
        elif rtype == "dref_seq":
            node_id, seq = data
            with self._lock:
                if seq:
                    cur = self._decref_seqs.get(node_id, 0)
                    self._decref_seqs[node_id] = max(cur, int(seq))
                else:
                    self._decref_seqs.pop(node_id, None)
        elif rtype == "incarnation":
            node_id, inc = data
            with self._lock:
                cur = self._incarnations.get(node_id, 0)
                self._incarnations[node_id] = max(cur, int(inc))
        elif rtype == "kv":
            ns, key, value = data
            with self._lock:
                self._kv[(ns, key)] = value
        elif rtype == "kv_del":
            ns, key = data
            with self._lock:
                self._kv.pop((ns, key), None)
        elif rtype == "contained":
            oid, ids = data
            with self._lock:
                if ids:
                    self._contained[oid] = list(ids)
                else:
                    self._contained.pop(oid, None)
        elif rtype == "dir+":
            oid, node_id, nbytes = data
            self.directory.add(oid, node_id, nbytes)
        elif rtype == "dir-":
            oid, node_id = data
            self.directory.remove(oid, node_id)
        elif rtype == "dir_purge":
            self.directory.purge_node(data)
        elif rtype == "actor":
            spec = data
            with self._lock:
                rec = self._actors.get(spec.actor_id)
                if rec is None:
                    self._actors[spec.actor_id] = ActorRecord(spec=spec)
                    if spec.name is not None:
                        self._named_actors[(spec.namespace,
                                            spec.name)] = spec.actor_id
        elif rtype == "actor_state":
            (actor_id, state, worker_id, node_id,
             death_cause, num_restarts) = data
            with self._lock:
                rec = self._actors.get(actor_id)
                if rec is not None:
                    rec.state = state
                    rec.worker_id = worker_id
                    rec.node_id = node_id
                    rec.death_cause = death_cause
                    rec.num_restarts = num_restarts
                    if state == DEAD and rec.spec.name is not None:
                        self._named_actors.pop(
                            (rec.spec.namespace, rec.spec.name), None)
        elif rtype == "node":
            node_id, resources, labels = data
            with self._lock:
                if node_id not in self._nodes:
                    self._nodes[node_id] = NodeTableRecord(
                        node_id=node_id, resources=dict(resources),
                        is_head=False, labels=dict(labels))
        elif rtype == "node_state":
            node_id, alive, cause = data
            with self._lock:
                rec = self._nodes.get(node_id)
                if rec is not None and not rec.is_head:
                    rec.alive = alive
                    if cause:
                        rec.death_cause = cause
        elif rtype == "pg":
            with self._lock:
                self._pgs[data["placement_group_id"]] = dict(data)
        # unknown record types from a newer head are skipped silently:
        # the snapshot they compact into still restores

    # ---- task events (GcsTaskManager parity) ----
    def record_task_event(self, task_id: str, name: str, state: str,
                          worker_id: str = "", error: str = "") -> None:
        with self._lock:
            self._task_events.append({
                "task_id": task_id, "name": name, "state": state,
                "worker_id": worker_id, "error": error, "ts": time.time(),
            })
        if state in TERMINAL_TASK_STATES:
            # the task is off the head's books: a restarted head
            # must not re-own (and re-place) it — terminal specs evict
            # eagerly (stripe pop), then the pop is logged
            if self._live_tasks.pop(task_id) is not None:
                self._walog("task_done", task_id)

    def record_task_events(self, events: list[dict]) -> None:
        """Batched ingest from worker-side event buffers (reference
        GcsTaskManager AddTaskEventData): events carry their own
        worker-side ts/duration_s."""
        with self._lock:
            self._task_events.extend(events)

    def list_task_events(self, limit: int = 1000) -> list[dict]:
        with self._lock:
            out = list(self._task_events)
        return out[-limit:]

    def summarize_tasks(self) -> dict:
        with self._lock:
            latest: dict[str, dict] = {}
            for ev in self._task_events:
                latest[ev["task_id"]] = ev
        counts: dict[str, int] = collections.defaultdict(int)
        for ev in latest.values():
            counts[ev["state"]] += 1
        return dict(counts)

    # ---- shard observability (r16) ----
    def shard_stats(self) -> dict:
        """Per-table stripe occupancy/contention for /metrics and the
        head_shard gauges: proves the striping spreads load instead of
        asserting it."""
        return {
            "refs": self._refs.stats(),
            "live_tasks": self._live_tasks.stats(),
            "lineage": dict(self._lineage.stats(),
                            evicted=self._lineage.evicted),
            "directory": self.directory.shard_stats(),
        }
