"""Head-side handle for a remote node agent.

Duck-types the per-node ``Scheduler`` interface the ClusterTaskManager
and Runtime drive (enqueue / cancel / bundles / resource views /
actor-task push), but the real scheduler + worker pool live in the
remote ``node_agent`` process; this proxy forwards over the agent's
control connection and mirrors routed work so the head can recover it
if the agent dies (reference: the GCS's per-node bookkeeping in
gcs_node_manager.h:62 + gcs_actor_manager, which re-places work when a
raylet is lost).

Resource views (avail / pending demand) come from agent heartbeats —
the RaySyncer role (reference common/ray_syncer/ray_syncer.h:88):
scheduling reads a slightly stale snapshot, and the authoritative
check happens agent-side at dispatch.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ray_tpu._private import protocol
from ray_tpu._private.specs import ActorSpec, ActorTaskSpec, TaskSpec

_RPC_TIMEOUT = 30.0


class RemoteNodeHandle:
    def __init__(self, node_id: str, conn: protocol.Connection,
                 resources: dict[str, float],
                 advertise_addr: tuple[str, int]):
        self.node_id = node_id
        self.conn = conn
        self.advertise_addr = advertise_addr
        self.total = dict(resources)
        self.avail = dict(resources)
        self._pending_demand: dict[str, float] = {}
        self._pending_shapes: list[dict[str, float]] = []
        self._idle = True
        self._lock = threading.Lock()
        # Mirror of work routed to this agent, keyed by task_id /
        # "actor:<id>"; value = (spec, dispatched: bool). drain_for_death
        # recovers from this when the agent vanishes.
        self._work: dict[str, tuple[Any, bool]] = {}
        # worker_id -> actor_id (or None) as reported by dispatch events.
        self._workers: dict[str, Optional[str]] = {}
        self.wire_stats: dict[str, int] = {}
        # object-plane counters (r8: transfers/serves/dedup/bytes) as
        # of the last heartbeat — aggregated by object_plane_stats
        self.object_plane: dict = {}
        # flight-recorder watermark as of the last heartbeat (r9
        # tracing plane: heartbeats carry ONLY the watermark; events
        # move via the trace_dump pull) — surfaced by trace_stats
        self.trace_watermark = 0
        self._dead = False

    # ------------------------------------------------------- heartbeat
    def on_heartbeat(self, msg: dict) -> None:
        with self._lock:
            self.avail = dict(msg.get("avail", self.avail))
            self.total = dict(msg.get("total", self.total))
            self._pending_demand = dict(msg.get("pending_demand", {}))
            self._pending_shapes = list(msg.get("pending_shapes", []))
            self._idle = bool(msg.get("is_idle", False))
            self._last_workers = list(msg.get("workers", []))
            # agent-process frame counters (r7 telemetry; {} from
            # pre-r7 agents) — debug surface for per-node wire load
            self.wire_stats = dict(msg.get("wire", {}))
            self.trace_watermark = int(msg.get("trace_watermark", 0))
            op = dict(msg.get("object_plane", {}))
            if op:
                # serves_per_object rides heartbeats only when it
                # changed agent-side: keep the last received table
                if ("serves_per_object" not in op
                        and "serves_per_object" in self.object_plane):
                    op["serves_per_object"] = (
                        self.object_plane["serves_per_object"])
                self.object_plane = op

    def workers_snapshot(self) -> list:
        """Worker table rows as of the last heartbeat."""
        with self._lock:
            return list(getattr(self, "_last_workers", []))

    # ------------------------------------------- scheduler duck-typing
    @staticmethod
    def need_of(spec) -> dict[str, float]:
        from ray_tpu._private.scheduler import Scheduler
        return Scheduler.need_of(spec)

    def effective_avail(self) -> dict[str, float]:
        with self._lock:
            eff = dict(self.avail)
            for k, v in self._pending_demand.items():
                eff[k] = eff.get(k, 0.0) - v
            return eff

    def pending_shapes(self) -> list[dict[str, float]]:
        with self._lock:
            return list(self._pending_shapes)

    def utilization(self) -> float:
        eff = self.effective_avail()
        u = 0.0
        for k, tot in self.total.items():
            if tot > 0:
                u = max(u, 1.0 - eff.get(k, 0.0) / tot)
        return u

    def is_idle(self) -> bool:
        with self._lock:
            return self._idle and not self._work

    def owns_worker(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._workers

    def worker_for_actor(self, actor_id: str) -> Optional[str]:
        with self._lock:
            for wid, aid in self._workers.items():
                if aid == actor_id:
                    return wid
        return None

    # ------------------------------------------------------- submission
    def _key(self, spec) -> str:
        if isinstance(spec, ActorSpec):
            return "actor:" + spec.actor_id
        return spec.task_id

    def enqueue(self, spec) -> None:
        with self._lock:
            self._work[self._key(spec)] = (spec, False)
        self._send({"type": protocol.NODE_ENQUEUE, "spec": spec})

    enqueue_front = enqueue

    def cancel_pending(self, task_id: str) -> Optional[TaskSpec]:
        with self._lock:
            entry = self._work.get(task_id)
        if entry is None or entry[1]:
            return None                    # unknown or already running
        try:
            rep = self.conn.request({"type": protocol.NODE_CANCEL_PENDING,
                                     "task_id": task_id},
                                    timeout=_RPC_TIMEOUT)
        except (protocol.ConnectionClosed, TimeoutError):
            return None
        if rep.get("found"):
            with self._lock:
                entry = self._work.pop(task_id, None)
            return entry[0] if entry else None
        return None

    def worker_running_task(self, task_id: str):
        with self._lock:
            entry = self._work.get(task_id)
            if entry is None or not entry[1]:
                return None
            spec = entry[0]
            wid = getattr(spec, "_worker_id", None)
        return (wid, spec) if wid is not None else None

    def cancel_running(self, worker_id: str, task_id: str) -> bool:
        return self._send({"type": protocol.NODE_CANCEL_RUNNING,
                           "worker_id": worker_id, "task_id": task_id})

    def kill_worker(self, worker_id: str) -> None:
        self._send({"type": protocol.NODE_KILL_WORKER,
                    "worker_id": worker_id})

    def send_actor_task(self, actor_worker_id: str,
                        spec: ActorTaskSpec) -> bool:
        """Fire-and-forget push (NO blocking reply: this is called from
        the agent connection's own reader thread when an actor goes
        ALIVE, and a request would deadlock against ourselves). If the
        agent can't deliver (worker gone) it sends an
        actor_task_undeliverable event and the head requeues."""
        return self._send({"type": protocol.NODE_SEND_ACTOR_TASK,
                           "worker_id": actor_worker_id, "spec": spec})

    # -------------------------------------------------------- bundles
    def reserve_bundle(self, pg_id: str, index: int,
                       resources: dict[str, float]) -> bool:
        try:
            rep = self.conn.request(
                {"type": protocol.NODE_RESERVE_BUNDLE, "pg_id": pg_id,
                 "index": index, "resources": resources},
                timeout=_RPC_TIMEOUT)
        except (protocol.ConnectionClosed, TimeoutError):
            return False
        if rep.get("ok"):
            # keep the cached view honest until the next heartbeat
            with self._lock:
                for k, v in resources.items():
                    self.avail[k] = self.avail.get(k, 0.0) - v
            return True
        return False

    def release_bundle(self, pg_id: str, index: int) -> None:
        self._send({"type": protocol.NODE_RELEASE_BUNDLE,
                    "pg_id": pg_id, "index": index})

    # ------------------------------------------------- event ingestion
    def on_dispatched(self, key: str, worker_id: str,
                      actor_id: Optional[str] = None) -> None:
        with self._lock:
            entry = self._work.get(key)
            if entry is not None:
                spec = entry[0]
                try:
                    spec._worker_id = worker_id
                except AttributeError:
                    pass
                self._work[key] = (spec, True)
            self._workers[worker_id] = actor_id

    def on_finished(self, key: str):
        """Remove + return the mirrored spec (None if unknown)."""
        with self._lock:
            entry = self._work.pop(key, None)
        return entry[0] if entry else None

    def track_live_actor(self, actor_id: str, spec) -> None:
        """Keep an ALIVE actor in the mirror so drain_for_death can
        restart it if this agent dies."""
        with self._lock:
            self._work["actor:" + actor_id] = (spec, True)

    def on_worker_lost(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:                     # NodeRecord protocol
        pass

    def drain_for_death(self):
        """(queued specs, running TaskSpecs, actor ids) from the mirror."""
        with self._lock:
            self._dead = True
            work = list(self._work.values())
            self._work.clear()
            self._workers.clear()
        queued = [s for s, dispatched in work if not dispatched]
        running = [s for s, dispatched in work
                   if dispatched and isinstance(s, TaskSpec)]
        actor_ids = [s.actor_id for s, dispatched in work
                     if dispatched and isinstance(s, ActorSpec)]
        try:
            self.conn.close()
        except Exception:
            pass
        return queued, running, actor_ids

    def die_silently(self) -> None:
        """Test hook parity: drop the control connection without drain
        (the health monitor must notice)."""
        try:
            self.conn.close()
        except Exception:
            pass

    def shutdown(self) -> None:
        self._send({"type": protocol.NODE_SHUTDOWN})
        try:
            self.conn.close()
        except Exception:
            pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "node_id": self.node_id, "remote": True,
                "total_resources": dict(self.total),
                "available_resources": dict(self.avail),
                "num_pending_tasks": len(self._pending_shapes),
                "mirrored_work": len(self._work),
            }

    # --------------------------------------------------------- helpers
    def _send(self, msg: dict) -> bool:
        try:
            self.conn.send(msg)
            return True
        except protocol.ConnectionClosed:
            return False
