"""Head-side handle for a remote node agent.

Duck-types the per-node ``Scheduler`` interface the ClusterTaskManager
and Runtime drive (enqueue / cancel / bundles / resource views /
actor-task push), but the real scheduler + worker pool live in the
remote ``node_agent`` process; this proxy forwards over the agent's
control connection and mirrors routed work so the head can recover it
if the agent dies (reference: the GCS's per-node bookkeeping in
gcs_node_manager.h:62 + gcs_actor_manager, which re-places work when a
raylet is lost).

Resource views (avail / pending demand) come from agent heartbeats —
the RaySyncer role (reference common/ray_syncer/ray_syncer.h:88):
scheduling reads a slightly stale snapshot, and the authoritative
check happens agent-side at dispatch.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG
from ray_tpu._private.specs import ActorSpec, ActorTaskSpec, TaskSpec

_RPC_TIMEOUT = 30.0


class RemoteNodeHandle:
    def __init__(self, node_id: str, conn: protocol.Connection,
                 resources: dict[str, float],
                 advertise_addr: tuple[str, int],
                 wal_log=None):
        self.node_id = node_id
        # Head-HA WAL hook (r15): mirror adds + lease grants are
        # logged so a restarted head rehydrates this node's routed
        # work; None when head persistence is off.
        self._wal = wal_log
        self.conn = conn
        self.advertise_addr = advertise_addr
        self.total = dict(resources)
        self.avail = dict(resources)
        self._pending_demand: dict[str, float] = {}
        self._pending_shapes: list[dict[str, float]] = []
        # Optimistic demand claims (r20): avail/_pending_demand only
        # refresh on heartbeats, so two back-to-back submits both read
        # the pre-claim snapshot and the hybrid pack phase lands them
        # on the SAME node — fatal for an ActorSpec, which (unlike a
        # TaskSpec) can never spill off a full queue. Each enqueue
        # claims its need here until the agent's own books catch up;
        # entries expire after a few beat periods, so a delta beat
        # that never re-sends an unchanged key can't leak a claim.
        self._optimistic: dict[str, tuple[dict, float]] = {}
        self._idle = True
        self._lock = threading.Lock()
        # Mirror of work routed to this agent, keyed by task_id /
        # "actor:<id>"; value = (spec, dispatched: bool). drain_for_death
        # recovers from this when the agent vanishes.
        self._work: dict[str, tuple[Any, bool]] = {}
        # worker_id -> actor_id (or None) as reported by dispatch events.
        self._workers: dict[str, Optional[str]] = {}
        self.wire_stats: dict[str, int] = {}
        # object-plane counters (r8: transfers/serves/dedup/bytes) as
        # of the last heartbeat — aggregated by object_plane_stats
        self.object_plane: dict = {}
        # flight-recorder watermark as of the last heartbeat (r9
        # tracing plane: heartbeats carry ONLY the watermark; events
        # move via the trace_dump pull) — surfaced by trace_stats
        self.trace_watermark = 0
        self._dead = False
        # r17: the incarnation minted at this registration (set by
        # cluster.add_remote_node); surfaced by liveness_stats.
        self.incarnation = 0
        # Drain state (r14): head-side routing flag — the agent itself
        # keeps running so in-flight work finishes and completions
        # flow; reclaim of its queued backlog goes through the r10
        # NODE_LEASE_REVOKE machinery (steal_candidates/revoke_lease).
        self.draining = False
        # ---- delegated bulk-lease dispatch (r10) ----
        # Specs parked for the next NODE_LEASE_BATCH flush. They are
        # ALREADY mirrored in _work (death recovery / cancel see them
        # immediately); the buffer only batches the wire send.
        self._lease_buf: list = []
        self._lease_lock = threading.Lock()
        # Serializes the pop-build-send of a lease batch: the "flush
        # before cancel/revoke" guards must not return while another
        # thread holds a popped-but-unsent batch, or the cancel frame
        # would overtake its own task's lease on the wire.
        self._lease_send_lock = threading.Lock()
        self._lease_flusher = protocol.FlushLoop(
            self.flush_leases,
            lambda: _CFG.delegate_lease_delay_ms,
            f"rtpu-lease-{node_id}")
        # task_ids granted to the agent and not yet reported done —
        # the outstanding count the delegate_max_inflight budget caps
        self._leased: set[str] = set()
        self._leases_sent = 0
        self._tasks_leased = 0
        # agent-reported delegate counters (ride heartbeats)
        self.delegate_stats: dict = {}
        # agent-reported direct-actor host counters (r18, heartbeat-
        # carried): served / nacks / served_bytes
        self.direct_stats: dict = {}
        # ---- N10 heartbeat delta-sync ----
        self._hb_seq = -1
        self._hb_last_resync = 0.0

    # ------------------------------------------------------- heartbeat
    def on_heartbeat(self, msg: dict) -> None:
        """Apply a heartbeat — full snapshot or an N10 delta. Deltas
        (hb_delta=True, MINOR >= 3 agents) carry ONLY the keys that
        changed since the previous beat; absent keys mean "unchanged",
        so application is update-if-present. A seq gap (dropped/
        reordered beat) applies best-effort and asks the agent for a
        full snapshot via NODE_HB_RESYNC; pre-delta agents send every
        key every beat and take the same path as a full snapshot."""
        seq = msg.get("hb_seq")
        gap = False
        with self._lock:
            if seq is not None:
                if msg.get("hb_delta") and seq != self._hb_seq + 1:
                    gap = True
                self._hb_seq = int(seq)
            if "avail" in msg:
                self.avail = dict(msg["avail"])
            if "total" in msg:
                self.total = dict(msg["total"])
            if "pending_demand" in msg:
                self._pending_demand = dict(msg["pending_demand"])
            if "pending_shapes" in msg:
                self._pending_shapes = list(msg["pending_shapes"])
            if "is_idle" in msg:
                self._idle = bool(msg["is_idle"])
            if "workers" in msg:
                self._last_workers = list(msg["workers"])
            # agent-process frame counters (r7 telemetry; {} from
            # pre-r7 agents) — debug surface for per-node wire load
            if "wire" in msg:
                self.wire_stats = dict(msg["wire"])
            if "trace_watermark" in msg:
                self.trace_watermark = int(msg["trace_watermark"])
            if "delegate" in msg:
                self.delegate_stats = dict(msg["delegate"])
            if "direct" in msg:
                self.direct_stats = dict(msg["direct"])
            op = dict(msg.get("object_plane", {}))
            if op:
                # serves_per_object rides heartbeats only when it
                # changed agent-side: keep the last received table
                if ("serves_per_object" not in op
                        and "serves_per_object" in self.object_plane):
                    op["serves_per_object"] = (
                        self.object_plane["serves_per_object"])
                self.object_plane = op
        if gap:
            now = time.monotonic()
            if now - self._hb_last_resync > 1.0:   # one ask per gap
                self._hb_last_resync = now
                self._send({"type": protocol.NODE_HB_RESYNC})

    def workers_snapshot(self) -> list:
        """Worker table rows as of the last heartbeat."""
        with self._lock:
            return list(getattr(self, "_last_workers", []))

    def direct_port_of(self, worker_id: str):
        """The worker's r18 direct-serving port as of the last
        heartbeat (None until a beat carries the worker's row —
        callers fall back to agent-hosted direct serving meanwhile)."""
        with self._lock:
            for row in getattr(self, "_last_workers", ()):
                if row.get("worker_id") == worker_id:
                    return row.get("direct_port")
        return None

    # ------------------------------------------- scheduler duck-typing
    @staticmethod
    def need_of(spec) -> dict[str, float]:
        from ray_tpu._private.scheduler import Scheduler
        return Scheduler.need_of(spec)

    def effective_avail(self) -> dict[str, float]:
        with self._lock:
            eff = dict(self.avail)
            for k, v in self._pending_demand.items():
                eff[k] = eff.get(k, 0.0) - v
            now = time.monotonic()
            for key in list(self._optimistic):
                need, deadline = self._optimistic[key]
                if deadline < now or key not in self._work:
                    del self._optimistic[key]
                    continue
                for k, v in need.items():
                    eff[k] = eff.get(k, 0.0) - v
            return eff

    def pending_shapes(self) -> list[dict[str, float]]:
        with self._lock:
            return list(self._pending_shapes)

    def utilization(self) -> float:
        eff = self.effective_avail()
        u = 0.0
        for k, tot in self.total.items():
            if tot > 0:
                u = max(u, 1.0 - eff.get(k, 0.0) / tot)
        return u

    def is_idle(self) -> bool:
        with self._lock:
            return self._idle and not self._work

    def owns_worker(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._workers

    def worker_for_actor(self, actor_id: str) -> Optional[str]:
        with self._lock:
            for wid, aid in self._workers.items():
                if aid == actor_id:
                    return wid
        return None

    # ------------------------------------------------------- submission
    def _key(self, spec) -> str:
        if isinstance(spec, ActorSpec):
            return "actor:" + spec.actor_id
        return spec.task_id

    def delegates(self) -> bool:
        """Delegated bulk-lease dispatch is on for this agent: enabled
        by config (RAY_TPU_DELEGATE) AND the agent demonstrated wire
        MINOR >= 3 (negotiated by observation, like BatchFrame)."""
        return bool(_CFG.delegate) and self.conn.peer_speaks_delegate()

    _OPTIMISTIC_TTL_S = 2.0          # = 4 agent heartbeat periods

    def enqueue(self, spec) -> None:
        key = self._key(spec)
        need = self.need_of(spec)
        with self._lock:
            self._work[key] = (spec, False)
            if any(need.values()):
                self._optimistic[key] = (
                    need, time.monotonic() + self._OPTIMISTIC_TTL_S)
            if self._wal is not None and isinstance(spec, TaskSpec):
                # the spec itself rides the task-submit record; this
                # marks WHERE it was routed (actor routing is derived
                # from the actor table at recovery instead)
                self._wal("madd", (self.node_id, spec.task_id))
        if isinstance(spec, TaskSpec) and self.delegates():
            self._park_lease(spec)
            return
        self._send({"type": protocol.NODE_ENQUEUE, "spec": spec})

    enqueue_front = enqueue

    # ---- bulk leases (r10) ----
    def _park_lease(self, spec: TaskSpec) -> None:
        """Park a spec for the next NODE_LEASE_BATCH: the first parked
        spec opens a delegate_lease_delay_ms collect window (shared
        FlushLoop pacer); hitting delegate_lease_batch flushes inline.
        Mirrors the wire-level coalescing queue's collect-then-flush
        shape one level up — whole specs instead of frames."""
        with self._lease_lock:
            self._lease_buf.append(spec)
            n = len(self._lease_buf)
        if n >= max(1, _CFG.delegate_lease_batch):
            self.flush_leases()
        else:
            self._lease_flusher.wake()

    def _budget_room(self) -> int:
        cap = _CFG.delegate_max_inflight
        if cap <= 0:
            return 1 << 30
        return max(0, cap - len(self._leased))

    def flush_leases(self) -> None:
        """Ship parked specs as ONE NODE_LEASE_BATCH (bounded by the
        outstanding-task budget; the remainder stays parked and
        re-flushes as done batches free room). Carries the head's
        resource-budget snapshot for the agent's lease ledger.

        The whole pop→build→send runs under _lease_send_lock: callers
        using this as an ordering barrier (cancel_pending /
        revoke_lease flush-first guards) must not observe an "empty"
        buffer while another thread still holds an unsent batch."""
        if self._dead:
            return                       # mirror already drained
        with self._lease_send_lock:
            self._flush_leases_locked()

    def _flush_leases_locked(self) -> None:
        with self._lease_lock:
            if not self._lease_buf:
                return
            room = self._budget_room()
            if room <= 0:
                return
            batch, self._lease_buf = (self._lease_buf[:room],
                                      self._lease_buf[room:])
            # drop specs cancel/death already removed from the mirror
            with self._lock:
                batch = [s for s in batch if s.task_id in self._work]
                self._leased.update(s.task_id for s in batch)
            if not batch:
                return
            lease_id = "ls_" + uuid.uuid4().hex[:12]
            self._leases_sent += 1
            self._tasks_leased += len(batch)
            if self._wal is not None:
                self._wal("lease",
                          (self.node_id, [s.task_id for s in batch]))
        if _tp.enabled():
            # one tiny "lease_batch" span per traced spec, spliced
            # between the driver's submit span and the agent-side
            # queue/lease spans (specs re-parent under it), so the
            # delegated hop reads off the timeline: submit ->
            # lease_batch -> queue -> lease -> exec -> done
            t_now = _tp.now()
            for s in batch:
                if getattr(s, "trace_id", 0):
                    sid = _tp.new_id()
                    _tp.record("head", "lease_batch", t_now, t_now,
                               s.trace_id, sid,
                               getattr(s, "parent_span", 0),
                               {"n": len(batch), "node": self.node_id})
                    s.parent_span = sid
        self._send({"type": protocol.NODE_LEASE_BATCH,
                    "lease_id": lease_id, "specs": batch,
                    "budget": self.effective_avail()})

    def kick_lease_flush(self) -> None:
        """Completions freed outstanding-budget room: retry the flush
        (no-op when nothing is parked)."""
        if self._lease_buf:
            self.flush_leases()

    def steal_candidates(self, limit: int = 64) -> list[str]:
        """Leased task_ids eligible for a rebalance revoke: plain
        tasks without node-affinity/PG constraints that haven't
        exhausted their spill budget (the same rules cluster.try_spill
        applies to local queues). The agent-side reclaim then filters
        to queued-NOT-started — running tasks always stay put."""
        out: list[str] = []
        with self._lock:
            for tid in self._leased:
                entry = self._work.get(tid)
                if entry is None:
                    continue
                spec = entry[0]
                if (getattr(spec, "node_id", None)
                        or getattr(spec, "placement_group_id", None)
                        or getattr(spec, "_spill_count", 0) >= 3):
                    continue
                out.append(tid)
                if len(out) >= limit:
                    break
        return out

    def queued_task_ids(self, limit: int = 4096) -> list[str]:
        """Drain-reclaim candidates (r14): every mirrored plain
        TaskSpec without affinity/PG constraints — the superset of
        ``steal_candidates`` that also covers specs PUSHED per-task
        when delegation is off (they sit in ``_work`` too, and the
        agent handles NODE_LEASE_REVOKE regardless of lease mode).
        No spill-budget filter: the node is dying, moving is
        mandatory. The agent-side reclaim still keeps anything
        already started."""
        out: list[str] = []
        with self._lock:
            for tid, entry in self._work.items():
                spec = entry[0]
                if not isinstance(spec, TaskSpec):
                    continue
                if (getattr(spec, "node_id", None)
                        or getattr(spec, "placement_group_id", None)):
                    continue
                out.append(tid)
                if len(out) >= limit:
                    break
        return out

    def revoke_lease(self, task_ids: list[str]) -> None:
        """Ask the agent to reclaim queued-not-started tasks (lease
        revoke / steal). Fire-and-forget BY DESIGN: the hand-back is
        the agent's ``lease_reclaimed`` NODE EVENT — buffered across
        head outages agent-side and deduped head-side by the mirror
        pop — so a slow or dropped reply can never strand work that
        already left the agent's queue (a request/reply here did
        exactly that on timeout). Tasks the agent already started stay
        leased there and complete normally."""
        self.flush_leases()      # revoke must not overtake its lease
        self._send({"type": protocol.NODE_LEASE_REVOKE,
                    "task_ids": list(task_ids)})

    def cancel_pending(self, task_id: str) -> Optional[TaskSpec]:
        with self._lock:
            entry = self._work.get(task_id)
        if entry is None or entry[1]:
            return None                    # unknown or already running
        # A spec still parked in the lease buffer (budget-saturated
        # flush left it behind) cancels locally — the agent has never
        # seen it, so the RPC below would miss and the task would
        # lease out and run later despite the cancel. Under the send
        # lock: no concurrent popped-but-unsent batch can hold it.
        with self._lease_send_lock:
            with self._lease_lock:
                for i, s in enumerate(self._lease_buf):
                    if s.task_id == task_id:
                        del self._lease_buf[i]
                        with self._lock:
                            entry = self._work.pop(task_id, None)
                        return entry[0] if entry else None
        self.flush_leases()  # the cancel must not overtake its lease
        try:
            rep = self.conn.request({"type": protocol.NODE_CANCEL_PENDING,
                                     "task_id": task_id},
                                    timeout=_RPC_TIMEOUT)
        except (protocol.ConnectionClosed, TimeoutError):
            return None
        if rep.get("found"):
            with self._lock:
                entry = self._work.pop(task_id, None)
                self._leased.discard(task_id)
            return entry[0] if entry else None
        return None

    def worker_running_task(self, task_id: str):
        with self._lock:
            entry = self._work.get(task_id)
            if entry is None:
                return None
            spec = entry[0]
            wid = getattr(spec, "_worker_id", None) if entry[1] else None
            delegated = task_id in self._leased
        if wid is not None:
            return (wid, spec)
        if not delegated:
            return None
        # Delegated mode suppresses per-task dispatch events, so the
        # mirror can't know the worker: ask the agent (cancel path
        # only — runs on a driver thread, never a reader).
        try:
            rep = self.conn.request({"type": protocol.NODE_FIND_TASK,
                                     "task_id": task_id},
                                    timeout=_RPC_TIMEOUT)
        except (protocol.ConnectionClosed, TimeoutError):
            return None
        if rep.get("state") == "running" and rep.get("worker_id"):
            return (rep["worker_id"], spec)
        return None

    def cancel_running(self, worker_id: str, task_id: str) -> bool:
        return self._send({"type": protocol.NODE_CANCEL_RUNNING,
                           "worker_id": worker_id, "task_id": task_id})

    def set_draining(self, flag: bool = True) -> None:
        """Head-side drain flag (see __init__); no wire round trip —
        drain is a routing decision the head alone enforces."""
        self.draining = bool(flag)

    def kill_worker(self, worker_id: str) -> None:
        self._send({"type": protocol.NODE_KILL_WORKER,
                    "worker_id": worker_id})

    def send_actor_task(self, actor_worker_id: str,
                        spec: ActorTaskSpec) -> bool:
        """Fire-and-forget push (NO blocking reply: this is called from
        the agent connection's own reader thread when an actor goes
        ALIVE, and a request would deadlock against ourselves). If the
        agent can't deliver (worker gone) it sends an
        actor_task_undeliverable event and the head requeues."""
        return self._send({"type": protocol.NODE_SEND_ACTOR_TASK,
                           "worker_id": actor_worker_id, "spec": spec})

    # -------------------------------------------------------- bundles
    def reserve_bundle(self, pg_id: str, index: int,
                       resources: dict[str, float]) -> bool:
        try:
            rep = self.conn.request(
                {"type": protocol.NODE_RESERVE_BUNDLE, "pg_id": pg_id,
                 "index": index, "resources": resources},
                timeout=_RPC_TIMEOUT)
        except (protocol.ConnectionClosed, TimeoutError):
            return False
        if rep.get("ok"):
            # keep the cached view honest until the next heartbeat
            with self._lock:
                for k, v in resources.items():
                    self.avail[k] = self.avail.get(k, 0.0) - v
            return True
        return False

    def release_bundle(self, pg_id: str, index: int) -> None:
        self._send({"type": protocol.NODE_RELEASE_BUNDLE,
                    "pg_id": pg_id, "index": index})

    # ------------------------------------------------- event ingestion
    def on_dispatched(self, key: str, worker_id: str,
                      actor_id: Optional[str] = None) -> None:
        with self._lock:
            entry = self._work.get(key)
            if entry is not None:
                spec = entry[0]
                try:
                    spec._worker_id = worker_id
                except AttributeError:
                    pass
                self._work[key] = (spec, True)
            self._workers[worker_id] = actor_id

    def on_finished(self, key: str):
        """Remove + return the mirrored spec (None if unknown)."""
        with self._lock:
            entry = self._work.pop(key, None)
            self._leased.discard(key)
        if self._lease_buf:
            self.kick_lease_flush()    # completion freed budget room
        return entry[0] if entry else None

    def track_live_actor(self, actor_id: str, spec) -> None:
        """Keep an ALIVE actor in the mirror so drain_for_death can
        restart it if this agent dies."""
        with self._lock:
            self._work["actor:" + actor_id] = (spec, True)

    def adopt_mirror(self, work: dict, leased) -> None:
        """Inherit mirrored work from a predecessor handle (r15): the
        rehydrated mirror of a pre-restart head, or the live mirror of
        the handle this re-registration replaces (a transient agent
        reconnect used to discard it — completions then popped
        nothing and accounting silently degraded)."""
        with self._lock:
            for key, entry in work.items():
                self._work.setdefault(key, entry)
            self._leased.update(leased)

    def on_worker_lost(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)

    # -------------------------------------------------------- lifecycle
    def start(self) -> None:                     # NodeRecord protocol
        pass

    def drain_for_death(self, close_conn: bool = True):
        """(queued specs, running TaskSpecs, actor ids) from the mirror.

        Delegated tasks (leased or still parked in the lease buffer)
        sit in the mirror with dispatched=False, so they all come back
        as "queued" and re-place through cluster.submit exactly once —
        the agent's workers died with it, so no completion can race a
        resubmission into a double execution.

        ``close_conn=False`` (r17, heartbeat-timeout deaths): the
        control connection is left OPEN. A node declared dead by
        staleness may be a partitioned zombie whose workers are still
        running — its post-heal frames must arrive (and be fenced by
        their stale incarnation, triggering the agent's reset +
        re-register) rather than vanish into a closed socket. The fd
        is released later by the agent's own close or process exit."""
        self._lease_flusher.stop()       # dead-before-wake, race-free
        with self._lease_lock:
            self._lease_buf.clear()
        with self._lock:
            self._dead = True
            self._leased.clear()
            work = list(self._work.values())
            self._work.clear()
            self._workers.clear()
        queued = [s for s, dispatched in work if not dispatched]
        running = [s for s, dispatched in work
                   if dispatched and isinstance(s, TaskSpec)]
        actor_ids = [s.actor_id for s, dispatched in work
                     if dispatched and isinstance(s, ActorSpec)]
        if close_conn:
            try:
                self.conn.close()
            except Exception:
                pass
        return queued, running, actor_ids

    def die_silently(self) -> None:
        """Test hook parity: drop the control connection without drain
        (the health monitor must notice)."""
        try:
            self.conn.close()
        except Exception:
            pass

    def shutdown(self) -> None:
        self._dead = True
        self._lease_flusher.stop()
        self._send({"type": protocol.NODE_SHUTDOWN})
        try:
            self.conn.close()
        except Exception:
            pass

    def stats(self) -> dict:
        with self._lock:
            return {
                "node_id": self.node_id, "remote": True,
                "total_resources": dict(self.total),
                "available_resources": dict(self.avail),
                "num_pending_tasks": len(self._pending_shapes),
                "mirrored_work": len(self._work),
                "delegated": self.delegates(),
                "leased_outstanding": len(self._leased),
                "lease_batches_sent": self._leases_sent,
                "tasks_leased": self._tasks_leased,
                "delegate_stats": dict(self.delegate_stats),
            }

    # --------------------------------------------------------- helpers
    def _send(self, msg: dict) -> bool:
        try:
            self.conn.send(msg)
            return True
        except protocol.ConnectionClosed:
            return False
