"""Worker process entry point + worker-side context.

Parity: the reference's `default_worker.py` + worker-side core worker
(reference python/ray/_private/workers/default_worker.py and
src/ray/core_worker/core_worker.cc RunTaskExecutionLoop:2840 /
ExecuteTask:2914). Execution flows through a thread pool whose width is the
actor's ``max_concurrency`` (concurrency-group parity,
core_worker/transport/concurrency_group_manager.cc, width only), so the
socket reader thread never runs user code and a worker blocked in a nested
``get`` keeps draining pushed messages.
"""
from __future__ import annotations

import argparse
import asyncio
import inspect
import os
import pickle
import socket
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import cloudpickle

from ray_tpu._private import context as _context
from ray_tpu._private import metrics_plane as _mp
from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.object_store import StoredObject, deserialize, serialize
from ray_tpu._private.refs import ObjectRef
from ray_tpu._private.specs import (ActorSpec, ActorTaskSpec, RefMarker,
                                    TaskSpec, extract_ref_args, function_id,
                                    new_actor_id, new_task_id)
from ray_tpu.exceptions import (GetTimeoutError, TaskError, format_exception)


class WorkerContext(_context.BaseContext):
    is_driver = False

    def __init__(self, conn: protocol.Connection, worker_id: str):
        self.conn = conn
        self.worker_id = worker_id
        self._sent_funcs: set[str] = set()
        # r18 direct actor caller: created lazily on the first actor
        # call once the peer has demonstrated wire MINOR >= 8 (the
        # delta flusher thread shouldn't exist in workers that never
        # call actors)
        self._direct = None
        self._direct_lock = threading.Lock()

    def _direct_caller(self):
        from ray_tpu._private.config import CONFIG
        if not CONFIG.direct_actor or \
                not self.conn.peer_speaks_direct_actor():
            return None
        with self._direct_lock:
            if self._direct is None:
                from ray_tpu._private import refs as _refs
                from ray_tpu._private.direct_actor import (
                    WorkerDirectCaller)
                self._direct = WorkerDirectCaller(self)
                # a released return ref drops its cached inline reply
                _refs.register_release_hook(self._direct.release)
            return self._direct

    # ---- object plane ----
    def put(self, value: Any) -> ObjectRef:
        with _tp.span("worker", "put"):
            return self._put_inner(value)

    def _put_inner(self, value: Any) -> ObjectRef:
        stored = serialize(value)
        rep = self.conn.request(_tp.stamp(
            {"type": protocol.PUT_OBJECT, "stored": stored}))
        if rep.get("pressure"):
            # store over cap and fully pinned: self-throttle the
            # producer (create-queueing backpressure applied in the
            # producer process, never on a connection reader)
            import time as _t
            _t.sleep(0.2)
        return ObjectRef(stored.object_id, owned=True)

    def get_objects(self, object_ids: list[str],
                    timeout: Optional[float]) -> list[Any]:
        out = []
        for oid in object_ids:
            value, stored = self._get_one(oid, timeout)
            if stored.is_error:
                self._note_actor_death(value)
                raise value
            out.append(value)
        return out

    def _note_actor_death(self, err) -> None:
        """An error about to surface to the caller: when it carries an
        ActorDiedError, invalidate the direct caller's endpoint cache
        for that actor so a restarted incarnation is re-resolved on
        the next call rather than NACK-discovered."""
        if self._direct is None:
            return
        from ray_tpu.exceptions import ActorDiedError
        cause = getattr(err, "cause", err)
        if isinstance(cause, ActorDiedError) and cause.actor_id:
            self._direct.on_actor_died(cause.actor_id)

    def _get_one(self, oid: str, timeout):
        # r18 direct plane: a return ref of a direct actor call
        # resolves against the inline-reply cache (zero frames). When
        # the reply is still in flight this waits on its future — with
        # a stall fallback onto the normal head path, which is where a
        # dead/partitioned host's calls resolve (the head errors its
        # mirrored in-flight entries with ActorDiedError).
        if self._direct is not None:
            t0 = time.monotonic()
            stored = self._direct.wait_inline(oid, timeout)
            if stored is not None:
                return deserialize(stored), stored
            if timeout is not None:
                # the head-routed fallback gets the REMAINING budget,
                # not a fresh one — get(timeout=T) must bound at ~T
                timeout = max(0.0, timeout
                              - (time.monotonic() - t0))
        for attempt in (0, 1):
            # stamped: the serving side (head/agent) parents its pull
            # spans under this get's span — arg pulls join the timeline
            reply = self.conn.request(_tp.stamp(
                {"type": protocol.GET_OBJECT, "object_id": oid,
                 "timeout": timeout}))
            if reply.get("timeout") or reply.get("stored") is None:
                raise GetTimeoutError(f"get() timed out waiting for {oid}")
            stored: StoredObject = reply["stored"]
            try:
                return deserialize(stored), stored
            except FileNotFoundError:
                # driver spilled the object between reply and our shm
                # map; one re-request restores it (inline buffers)
                if attempt:
                    raise

    def wait(self, object_ids: list[str], num_returns: int,
             timeout: Optional[float]):
        reply = self.conn.request(
            {"type": protocol.WAIT, "object_ids": object_ids,
             "num_returns": num_returns, "timeout": timeout})
        ready = set(reply.get("ready", []))
        return ([o for o in object_ids if o in ready],
                [o for o in object_ids if o not in ready])

    def decref(self, object_id: str) -> None:
        try:
            self.conn.send_lazy({"type": protocol.DECREF,
                                 "object_id": object_id})
        except protocol.ConnectionClosed:
            pass

    def decref_batch(self, object_ids: list[str]) -> None:
        # one frame for the whole flush batch (refs.py decref flusher)
        if not object_ids:
            return
        try:
            self.conn.send_lazy({"type": protocol.DECREF_BATCH,
                                 "object_ids": list(object_ids)})
        except protocol.ConnectionClosed:
            pass

    def addref(self, object_id: str) -> None:
        # lazy is safe: the ADDREF and any later TASK_DONE share the
        # coalescing queue (FIFO), and eager requests flush it first —
        # the pin-release ordering invariant holds either way
        try:
            self.conn.send_lazy({"type": protocol.ADDREF,
                                 "object_id": object_id})
        except protocol.ConnectionClosed:
            pass

    # ---- task plane (nested submission) ----
    def submit_task(self, spec: TaskSpec, func_bytes: bytes = None) -> list[str]:
        fb = None
        if spec.func_id not in self._sent_funcs:
            fb = func_bytes
            self._sent_funcs.add(spec.func_id)
        # nested submission inside a traced task: the child task's
        # trace chains under this worker-side submit span (the head's
        # own submit span then chains under it in turn)
        with _tp.span("submit", spec.name or spec.task_id) as tr:
            if tr is not None:
                spec.trace_id, spec.parent_span = tr
            self.conn.request({"type": protocol.SUBMIT, "spec": spec,
                               "func_bytes": fb})
        return spec.return_ids

    def create_actor(self, spec: ActorSpec, class_bytes: bytes = None) -> str:
        fb = None
        if spec.class_id not in self._sent_funcs:
            fb = class_bytes
            self._sent_funcs.add(spec.class_id)
        self.conn.request({"type": protocol.SUBMIT_ACTOR, "spec": spec,
                           "class_bytes": fb})
        return spec.actor_id

    def submit_actor_task(self, actor_id: str,
                          spec: ActorTaskSpec) -> list[str]:
        with _tp.span("submit", spec.name or spec.task_id) as tr:
            if tr is not None:
                spec.trace_id, spec.parent_span = tr
            # return-id borrows register eagerly ahead of the submit
            # on BOTH routes (lazy ADDREFs coalesce with neighboring
            # frames): the borrow must be structurally ordered before
            # any decref this process later emits for the same ref
            for oid in spec.return_ids:
                self.addref(oid)
            # r18: peer-to-peer fast path — resolve the actor's
            # endpoint once, stream the call to its host, take the
            # reply inline; falls back to the head-routed submit
            # whenever the direct plane declines the call
            d = self._direct_caller()
            if d is not None and d.submit(actor_id, spec):
                return spec.return_ids
            self.conn.request({"type": protocol.SUBMIT_ACTOR_TASK,
                               "actor_id": actor_id, "spec": spec})
        return spec.return_ids

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self.state_op("kill_actor", actor_id=actor_id)

    def cancel_task(self, object_id: str, force: bool = False) -> None:
        self.state_op("cancel_task", object_id=object_id, force=force)

    # ---- control plane ----
    def kv_op(self, op: str, key: str, value: Any = None,
              namespace: str = "default", **kw) -> Any:
        reply = self.conn.request({"type": protocol.KV_OP, "op": op,
                                   "key": key, "value": value,
                                   "namespace": namespace, **kw})
        return reply.get("value")

    def get_function(self, func_id: str) -> bytes:
        return self.kv_op("func_get", func_id)

    def state_op(self, op: str, **kwargs) -> Any:
        reply = self.conn.request({"type": protocol.STATE_OP, "op": op,
                                   "kwargs": kwargs})
        if reply.get("stale"):
            from ray_tpu._private.pubsub import StaleCursorError
            raise StaleCursorError(reply.get("detail", "stale cursor"),
                                   resync=reply.get("resync", 0))
        if reply.get("error"):
            raise RuntimeError(
                f"state op {op!r} failed on the head: {reply['error']}")
        return reply.get("value")

    def get_actor_handle(self, name: str, namespace: str = "default"):
        actors = self.state_op("list_actors")
        for a in actors:
            if a["name"] == name and a["state"] != "DEAD":
                cls = pickle.loads(self.get_function(a["class_id"]))
                from ray_tpu.actor import ActorHandle
                return ActorHandle._from_class(a["actor_id"], cls, 0)
        raise ValueError(f"No actor named {name!r}")

    def node_resources(self) -> dict:
        return self.state_op("cluster_resources")


def _apply_runtime_env(renv: Optional[dict], kv_get=None) -> dict:
    """Apply a runtime_env in this process; returns undo info.

    Parity: reference _private/runtime_env/ plugins: env_vars fanout,
    working_dir (chdir + sys.path), pip (per-host cached venv,
    runtime_env/pip.py) and py_modules (KV-shipped packages,
    runtime_env/py_modules.py); the key set is validated at SUBMISSION
    time (api.validate_runtime_env). Atomic: a failure mid-apply
    reverts whatever was already applied before re-raising — a pooled
    worker must never leak a half-applied env onto later tasks."""
    undo: dict = {"env": {}, "cwd": None, "paths": []}
    if not renv:
        return undo
    try:
        for k, v in (renv.get("env_vars") or {}).items():
            undo["env"][k] = os.environ.get(k)
            os.environ[k] = str(v)
        wd = renv.get("working_dir")
        if wd:
            undo["cwd"] = os.getcwd()
            os.chdir(wd)
            sys.path.insert(0, wd)
            undo["paths"].append(wd)
        if renv.get("pip"):
            from ray_tpu._private.runtime_env import ensure_pip_env
            site = ensure_pip_env(renv["pip"])
            sys.path.insert(0, site)
            undo["paths"].append(site)
        if renv.get("uv"):
            from ray_tpu._private.runtime_env import ensure_uv_env
            site = ensure_uv_env(renv["uv"])
            sys.path.insert(0, site)
            undo["paths"].append(site)
        if renv.get("conda"):
            from ray_tpu._private.runtime_env import ensure_conda_env
            site = ensure_conda_env(renv["conda"])
            sys.path.insert(0, site)
            undo["paths"].append(site)
        # container/image_uri is a spawn-time concern (the scheduler
        # wraps the worker command); nothing to apply in-process
        if renv.get("py_modules"):
            from ray_tpu._private.runtime_env import ensure_py_modules
            for path in ensure_py_modules(renv["py_modules"], kv_get):
                sys.path.insert(0, path)
                undo["paths"].append(path)
    except BaseException:
        _revert_runtime_env(undo)
        raise
    return undo


def _revert_runtime_env(undo: dict) -> None:
    for k, old in undo["env"].items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old
    if undo["cwd"] is not None:
        os.chdir(undo["cwd"])
    for path in undo.get("paths", []):
        try:
            sys.path.remove(path)
        except ValueError:
            pass


class WorkerExecutor:
    def __init__(self, ctx: WorkerContext):
        self.ctx = ctx
        self._fn_cache: dict[str, Any] = {}
        self._running_tasks: dict[str, threading.Thread] = {}
        # runtime env stays APPLIED between tasks with the same hash
        # (runtime-env-keyed worker reuse, reference worker_pool.cc);
        # a task with a different env reverts + re-applies
        self._cur_env_hash = None
        self._cur_env_undo: dict = {"env": {}, "cwd": None, "paths": []}
        self._pending_cancels: set[str] = set()
        self._cancel_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="rtpu-exec")
        self._actor: Any = None
        self._actor_spec: Optional[ActorSpec] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self.stop_event = threading.Event()
        # worker-side task-event buffer: execution-truth timestamps
        # (queue/env latency = gap vs the driver's RUNNING event),
        # batched + flushed periodically instead of one RPC per event
        # (reference src/ray/core_worker/task_event_buffer.cc)
        self._event_buf: list[dict] = []
        self._event_lock = threading.Lock()
        self._event_last_flush = time.time()
        self._event_flush_s = float(
            os.environ.get("RAY_TPU_TASK_EVENT_FLUSH_S", "2.0"))
        self._event_cap = int(
            os.environ.get("RAY_TPU_TASK_EVENT_BUFFER", "32"))
        threading.Thread(target=self._event_flush_loop,
                         name="rtpu-task-events", daemon=True).start()
        # pipelined-task steal-back (see UNQUEUE_TASK): tasks the driver
        # reclaimed before they started; _run_task skips them silently.
        # _queued_tasks tracks ids received but NOT yet started — the
        # steal may only succeed against those; replying ok to a task
        # that already ran would leave a poisoned tombstone that
        # silently skips a lineage-resubmitted task with the same id.
        self._queue_lock = threading.Lock()
        self._queued_tasks: set[str] = set()
        self._started_tasks: set[str] = set()
        self._unqueued_tasks: set[str] = set()
        # tasks/actor-calls accepted but not yet completion-reported:
        # TASK_DONE coalesces (lazy) only while OTHER work is in
        # flight — a lone sync round-trip must not eat the ~1 ms
        # coalescing window
        self._inflight = 0
        # r18 worker-direct serving: callers that dialed this worker's
        # own listener, awaiting an inline reply (task_id -> (conn,
        # rid)); the listener port rides the REGISTER frame so the
        # head can resolve this worker as the actor's endpoint
        self._direct_replies: dict[str, tuple] = {}
        self._direct_lock = threading.Lock()
        self._direct_listener = None
        self._direct_port = None

    # ---- direct actor call serving (r18) ----
    def start_direct_server(self):
        """Open this worker's direct-call listener (caller -> worker
        -> caller, no agent hop); returns the port for the REGISTER
        frame, or None (plane off / bind failed — callers fall back
        to agent-hosted serving)."""
        from ray_tpu._private.config import CONFIG
        if not (CONFIG.direct_actor and CONFIG.direct_actor_worker):
            return None
        try:
            lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lsock.setsockopt(socket.SOL_SOCKET,
                             socket.SO_REUSEADDR, 1)
            lsock.bind(("0.0.0.0", 0))
            lsock.listen(64)
        except OSError:
            return None
        self._direct_listener = lsock
        self._direct_port = lsock.getsockname()[1]
        threading.Thread(target=self._direct_accept_loop,
                         name="rtpu-worker-direct",
                         daemon=True).start()
        return self._direct_port

    def _direct_accept_loop(self) -> None:
        while not self.stop_event.is_set():
            try:
                sock, _ = self._direct_listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock, self._handle_direct,
                                       name="worker-direct",
                                       server=True)
            conn.start()

    def _handle_direct(self, conn: protocol.Connection,
                       msg: dict) -> None:
        """Messages from direct-dialed callers. Validation IS the
        fence: the worker id is unique per process, so a stale
        endpoint (actor restarted -> new worker/new port) can never
        validate here — it NACKs redirect-to-head."""
        mtype = msg["type"]
        if mtype == protocol.ACTOR_TASK_DIRECT:
            from ray_tpu._private import direct_actor as _da
            spec = msg["spec"]
            aspec = self._actor_spec
            if (msg.get("worker_id") != self.ctx.worker_id
                    or self._actor is None or aspec is None
                    or aspec.actor_id != msg.get("actor_id")):
                _da.nack(conn, msg.get("rid"),
                         "stale_worker_endpoint", False)
                return
            with self._direct_lock:
                self._direct_replies[spec.task_id] = (conn,
                                                      msg.get("rid"))
            self._accept_actor_task(spec, msg)
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    def _reply_direct(self, ent: tuple, task_id: str,
                      stored_list: list, error: bool,
                      extra: dict) -> None:
        """Answer a direct caller inline. Small results (and errors)
        ride the reply; large ones go to the node store via a
        direct_located TASK_DONE so the ordinary directory + pull
        path serves every getter — the reply itself stays small."""
        from ray_tpu._private.config import CONFIG
        from ray_tpu._private.object_transfer import materialize
        conn, rid = ent
        inline, big = [], []
        for s in stored_list:
            if (s.nbytes <= CONFIG.remote_inline_max_bytes
                    or s.is_error):
                inline.append(materialize(s))
                from ray_tpu._private.object_store import \
                    unlink_segment
                for name in s.shm_names:
                    unlink_segment(name)
            else:
                big.append(s)
        try:
            conn.reply({"rid": rid}, inline=inline, located=[],
                       error=error)
        except protocol.ConnectionClosed:
            # caller died mid-call: ship the small results through the
            # node store too (the direct_located path below), so a
            # third-party holder of the return ref still resolves
            big = big + inline
        if big:
            try:
                self.ctx.conn.send(
                    {"type": protocol.TASK_DONE, "task_id": task_id,
                     "results": big, "error": error,
                     "is_actor_task": True, "direct_located": True,
                     "actor_id": extra.get("actor_id"),
                     "name": extra.get("name")})
            except protocol.ConnectionClosed:
                pass

    # ---- message entry (called on reader thread) ----
    def handle(self, conn: protocol.Connection, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == protocol.TASK:
            spec = msg["spec"]
            self._stamp_recv(spec, msg)
            with self._queue_lock:
                self._queued_tasks.add(spec.task_id)
                self._inflight += 1
            self._pool.submit(self._run_task, spec)
        elif mtype == protocol.ACTOR_CREATE:
            spec: ActorSpec = msg["spec"]
            if spec.max_concurrency > 1:
                self._pool = ThreadPoolExecutor(
                    max_workers=spec.max_concurrency,
                    thread_name_prefix="rtpu-actor")
            self._pool.submit(self._create_actor, spec)
        elif mtype == protocol.ACTOR_TASK:
            self._accept_actor_task(msg["spec"], msg)
        elif mtype == protocol.CANCEL_TASK:
            self._cancel_running(msg["task_id"])
        elif mtype == protocol.UNQUEUE_TASK:
            # driver steals back a task pipelined behind a BLOCKED task
            # (it would deadlock if the blocked get transitively depends
            # on it). ok only for a task that is genuinely queued and
            # not started — a task that already started OR already
            # COMPLETED (raced ahead of the steal decision) must refuse,
            # or the tombstone would skip a future lineage resubmission
            # of the same task id and hang its caller's get().
            tid = msg["task_id"]
            with self._queue_lock:
                if tid in self._queued_tasks:
                    self._queued_tasks.discard(tid)
                    self._unqueued_tasks.add(tid)
                    ok = True
                else:
                    ok = False
            conn.reply(msg, ok=ok)
        elif mtype == protocol.TRACE_DUMP:
            conn.reply(msg, dump=_tp.dump())
        elif mtype == protocol.METRICS_DUMP:
            conn.reply(msg, dump=_mp.local_dump())
        elif mtype == protocol.SHUTDOWN:
            self.stop_event.set()
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    def _accept_actor_task(self, aspec: ActorTaskSpec,
                           msg: dict) -> None:
        """Queue one actor call for execution — shared by the classic
        pushed ACTOR_TASK and the r18 direct-dialed path (one entry
        point keeps the per-handle FIFO/async dispatch identical on
        both transports)."""
        self._stamp_recv(aspec, msg)
        with self._queue_lock:
            self._inflight += 1
        method = getattr(type(self._actor), aspec.method_name, None) \
            if self._actor is not None else None
        if method is not None and inspect.iscoroutinefunction(method):
            self._ensure_loop()
            asyncio.run_coroutine_threadsafe(
                self._run_actor_task_async(aspec), self._loop)
        else:
            self._pool.submit(self._run_actor_task, aspec)

    @staticmethod
    def _stamp_recv(spec, msg: dict) -> None:
        """Note message-arrival time and re-parent the spec under the
        scheduler's envelope-carried lease span, so the exec spans
        chain driver → scheduler → worker (the spec's own pickled
        parent is the submit span — the right fallback when the lease
        hop was emitted by an old peer or with tracing off there)."""
        tid = getattr(spec, "trace_id", 0)   # pre-r9-pickled specs
        if tid and _tp.enabled():            # have no trace fields
            tr = msg.get("_trace")
            if tr and tr[0] == tid:
                spec.parent_span = tr[1]
            spec._recv_ns = _tp.now()

    # ---- worker-side task events ----
    def _record_event(self, task_id: str, name: str, state: str,
                      **extra) -> None:
        ev = {"task_id": task_id, "name": name, "state": state,
              "ts": time.time(), "worker_id": self.ctx.worker_id,
              **extra}
        with self._event_lock:
            self._event_buf.append(ev)
            should = (len(self._event_buf) >= self._event_cap
                      or time.time() - self._event_last_flush
                      >= self._event_flush_s)
            if should:
                # claim the window now so a burst of events doesn't
                # spawn one flush thread each before the first one runs
                self._event_last_flush = time.time()
        if should:
            # never block the caller (async actors record from the
            # event-loop thread): flush on a short-lived thread
            threading.Thread(target=self.flush_events,
                             daemon=True).start()

    def _event_flush_loop(self) -> None:
        while not self.stop_event.wait(self._event_flush_s):
            self.flush_events()

    def flush_events(self) -> None:
        with self._event_lock:
            if not self._event_buf:
                return
            batch, self._event_buf = self._event_buf, []
            self._event_last_flush = time.time()
        try:
            self.ctx.state_op("record_task_events", events=batch)
        except Exception:
            pass   # head unreachable (shutdown race): best-effort

    def _cancel_running(self, task_id: str) -> None:
        """Interrupt a running task by raising TaskCancelledError in its
        executor thread (reference CancelTask path: the worker raises in
        the executing thread; tasks blocked in C extensions only observe
        it at the next bytecode boundary — same limitation there)."""
        import ctypes

        from ray_tpu.exceptions import TaskCancelledError
        with self._cancel_lock:
            # registration is popped under this same lock with the
            # pending-exception cleared, so a cancel can never land on a
            # thread after its task is done (it would brick the reused
            # pool thread)
            thread = self._running_tasks.get(task_id)
            if thread is None or not thread.is_alive():
                # Cancel raced ahead of registration (the pool thread
                # hasn't started the task yet): record it so _run_task
                # aborts before user code runs instead of silently
                # completing while the driver shows CANCELLING. Bounded:
                # a cancel that arrives AFTER completion leaves a stale
                # id here (its task never runs again), so cap the set.
                if len(self._pending_cancels) >= 1024:
                    self._pending_cancels.pop()
                self._pending_cancels.add(task_id)
                return
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(thread.ident),
                ctypes.py_object(TaskCancelledError))

    def _ensure_loop(self) -> None:
        if self._loop is None:
            self._loop = asyncio.new_event_loop()
            threading.Thread(target=self._loop.run_forever,
                             name="rtpu-actor-loop", daemon=True).start()

    # ---- tracing plane (r9) ----
    @staticmethod
    def _open_exec_span(spec, set_tls: bool = True):
        """Start the worker's span pair for a traced spec: a "recv"
        span covering message-arrival → execution-start (the worker-
        local FIFO queue time, which depth>1 pipelining makes real),
        then the exec span whose id the TASK_DONE will carry. Returns
        opaque state for _close_exec_span, or None when untraced."""
        tid = getattr(spec, "trace_id", 0)
        if not tid or not _tp.enabled():
            return None
        t_start = _tp.now()
        parent = getattr(spec, "parent_span", 0)
        t_recv = getattr(spec, "_recv_ns", None)
        if t_recv is not None:
            sid_r = _tp.new_id()
            _tp.record("worker", "recv", t_recv, t_start, tid, sid_r,
                       parent)
            parent = sid_r
        exec_sid = _tp.new_id()
        if set_tls:
            # nested gets/puts/submissions made by user code parent
            # under the exec span (async actor methods skip this: the
            # event loop interleaves coroutines on one thread)
            _tp.set_current(tid, exec_sid)
        return (tid, exec_sid, parent, t_start, set_tls)

    @staticmethod
    def _close_exec_span(tctx, spec, error: bool):
        """Record the exec span; returns the (trace_id, span_id) pair
        the TASK_DONE message should carry, or None."""
        if tctx is None:
            return None
        tid, exec_sid, parent, t_start, set_tls = tctx
        _tp.record("worker", "exec:" + (spec.name or spec.task_id[:12]),
                   t_start, _tp.now(), tid, exec_sid, parent,
                   {"error": True} if error else None)
        if set_tls:
            _tp.clear_current()
        return (tid, exec_sid)

    # ---- execution ----
    def _load_function(self, func_id: str):
        fn = self._fn_cache.get(func_id)
        if fn is None:
            data = self.ctx.get_function(func_id)
            if data is None:
                raise RuntimeError(f"function {func_id} not found in store")
            fn = cloudpickle.loads(data)
            self._fn_cache[func_id] = fn
        return fn

    def _resolve_args(self, args, kwargs):
        ref_ids = [a.object_id for a in args if isinstance(a, RefMarker)]
        ref_ids += [v.object_id for v in kwargs.values()
                    if isinstance(v, RefMarker)]
        values = {}
        if ref_ids:
            # traced tasks get an explicit arg-fetch span (the classic
            # hidden stall: remote args pulled before exec can start);
            # the GET_OBJECT messages inside carry its context
            with _tp.span("worker", "get_args",
                          extra={"n": len(ref_ids)}):
                got = self.ctx.get_objects(ref_ids, timeout=None)
            values = dict(zip(ref_ids, got))
        conv = lambda v: values[v.object_id] if isinstance(v, RefMarker) else v
        return tuple(conv(a) for a in args), {
            k: conv(v) for k, v in kwargs.items()}

    def _send_results(self, task_id: str, return_ids: list[str],
                      result: Any, num_returns: int, error: bool,
                      **extra) -> None:
        tr = extra.get("_trace")
        t_put = _tp.now() if (tr and _tp.enabled()) else None
        if not error and num_returns > 1:
            if not isinstance(result, (tuple, list)) or \
                    len(result) != num_returns:
                error = True
                result = TaskError(ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{type(result).__name__}"))
        stored_list = []
        if error or num_returns <= 1:
            values = [result] * len(return_ids)
        else:
            values = list(result)
        for oid, value in zip(return_ids, values):
            try:
                stored = serialize(value, object_id=oid)
            except BaseException as e:  # noqa: BLE001
                # Unserializable result (or shm failure): the task must
                # still complete with an error, never vanish silently
                # with its resources held.
                error = True
                stored = serialize(
                    TaskError(e, format_exception(e)), object_id=oid)
            stored.is_error = error
            stored_list.append(stored)
        if t_put is not None:
            # result serialization/seal span, parented under exec
            _tp.record("worker", "put", t_put, _tp.now(), tr[0],
                       _tp.new_id(), tr[1],
                       {"nbytes": sum(s.nbytes for s in stored_list)})
        # Lazy while other work is in flight: completions emitted in
        # the same tick (pipelined tasks finishing back-to-back, seal
        # notifications, trailing decrefs) coalesce into one frame —
        # the ~1 ms window is far below the driver's completion-
        # processing latency and the worker keeps executing meanwhile.
        # A lone completion (sync round-trip) flushes eagerly instead.
        with self._queue_lock:
            self._inflight = max(0, self._inflight - 1)
            busy = self._inflight > 0
        if extra.get("is_actor_task"):
            # r18 worker-direct: this call's caller dialed us — the
            # completion goes back inline on its connection, never
            # through the agent/head
            with self._direct_lock:
                ent = self._direct_replies.pop(task_id, None)
            if ent is not None:
                self._reply_direct(ent, task_id, stored_list, error,
                                   extra)
                return
        msg = {"type": protocol.TASK_DONE, "task_id": task_id,
               "results": stored_list, "error": error, **extra}
        if busy:
            self.ctx.conn.send_lazy(msg)
        else:
            self.ctx.conn.send(msg)

    def _finish_task_cleanup(self, spec: TaskSpec) -> None:
        """Idempotent post-task cleanup: deregister from the cancel
        table, CLEAR any pending async cancel on this thread (a raced
        cancel must not detonate in the pool thread's idle loop or in
        _send_results), and revert the task's runtime env."""
        import ctypes
        with self._cancel_lock:
            self._running_tasks.pop(spec.task_id, None)
            self._pending_cancels.discard(spec.task_id)
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_long(threading.get_ident()), None)


    def _switch_runtime_env(self, renv: Optional[dict]) -> None:
        from ray_tpu._private.runtime_env import env_hash
        h = env_hash(renv)
        if h == self._cur_env_hash:
            return
        _revert_runtime_env(self._cur_env_undo)
        # two envs may ship DIFFERENT versions of the same package:
        # purge modules imported from the reverted paths or the next
        # env would silently serve stale code
        for path in self._cur_env_undo.get("paths", []):
            prefix = os.path.abspath(path) + os.sep
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and os.path.abspath(f).startswith(prefix):
                    del sys.modules[name]
        self._cur_env_undo = {"env": {}, "cwd": None, "paths": []}
        self._cur_env_hash = None
        self._cur_env_undo = _apply_runtime_env(
            renv, kv_get=lambda k: self.ctx.kv_op("get", k))
        self._cur_env_hash = h

    def _run_task(self, spec: TaskSpec) -> None:
        from ray_tpu.exceptions import TaskCancelledError
        with self._queue_lock:
            self._queued_tasks.discard(spec.task_id)
            if spec.task_id in self._unqueued_tasks:
                # stolen back by the driver while queued: it was (or
                # will be) re-dispatched elsewhere — skip silently
                self._unqueued_tasks.discard(spec.task_id)
                self._inflight = max(0, self._inflight - 1)
                return
            self._started_tasks.add(spec.task_id)
        t0 = time.time()
        t0m = time.monotonic()      # exec histogram: step-immune clock
        tctx = self._open_exec_span(spec)
        self._record_event(spec.task_id, spec.name, "EXEC_STARTED")
        try:
            try:
                with self._cancel_lock:
                    if spec.task_id in self._pending_cancels:
                        self._pending_cancels.discard(spec.task_id)
                        raise TaskCancelledError(spec.task_id)
                    self._running_tasks[spec.task_id] = \
                        threading.current_thread()
                # env first: the function/args may only UNPICKLE under
                # the declared working_dir/env (the actor path does the
                # same). Kept applied for reuse by same-env tasks.
                self._switch_runtime_env(
                    getattr(spec, "runtime_env", None))
                fn = self._load_function(spec.func_id)
                args, kwargs = self._resolve_args(spec.args, spec.kwargs)
                result = fn(*args, **kwargs)
                error = False
            except BaseException as e:  # noqa: BLE001
                result = e if isinstance(e, TaskError) else TaskError(
                    e, format_exception(e), task_name=spec.name)
                error = True
            finally:
                self._finish_task_cleanup(spec)
        except TaskCancelledError as e:
            # the async cancel landed INSIDE the finally (between task
            # completion and the pending-exc clear): redo the cleanup —
            # the exception has fired, so this pass cannot be interrupted
            # again — and report the task cancelled.
            self._finish_task_cleanup(spec)
            result = TaskError(e, format_exception(e),
                               task_name=spec.name)
            error = True
        tr = self._close_exec_span(tctx, spec, error)
        _mp.observe_exec(time.monotonic() - t0m)
        extra = {"name": spec.name}
        if tr is not None:
            extra["_trace"] = tr
        self._send_results(spec.task_id, spec.return_ids, result,
                           spec.num_returns, error, **extra)
        self._record_event(spec.task_id, spec.name,
                           "EXEC_FAILED" if error else "EXEC_FINISHED",
                           duration_s=time.time() - t0)
        with self._queue_lock:
            self._started_tasks.discard(spec.task_id)
            # completion purges any stale steal tombstone so a lineage
            # resubmission reusing this task id can never be skipped
            self._unqueued_tasks.discard(spec.task_id)

    def _create_actor(self, spec: ActorSpec) -> None:
        try:
            # permanent: this worker is dedicated to the actor for life
            self._switch_runtime_env(getattr(spec, "runtime_env", None))
            cls = self._load_function(spec.class_id)
            args, kwargs = self._resolve_args(spec.init_args,
                                              spec.init_kwargs)
            self._actor = cls(*args, **kwargs)
            self._actor_spec = spec
            err = False
            err_repr = ""
        except BaseException as e:  # noqa: BLE001
            err = True
            err_repr = format_exception(e)
            sys.stderr.write(f"actor creation failed:\n{err_repr}")
        self.ctx.conn.send({"type": protocol.TASK_DONE,
                            "task_id": f"create:{spec.actor_id}",
                            "results": [], "error": err,
                            "error_repr": err_repr,
                            "is_actor_create": True,
                            "actor_id": spec.actor_id})

    def _invoke_actor_method(self, spec: ActorTaskSpec):
        args, kwargs = self._resolve_args(spec.args, spec.kwargs)
        if spec.method_name == "__rtpu_apply__":
            # escape hatch (reference actor.__ray_call__): run an
            # arbitrary function against the actor instance — compiled
            # DAGs use it to install their channel exec loops on user
            # actors without requiring cooperation from the class
            fn = cloudpickle.loads(args[0])
            return fn(self._actor, *args[1:], **kwargs)
        method = getattr(self._actor, spec.method_name)
        return method(*args, **kwargs)

    def _run_actor_task(self, spec: ActorTaskSpec) -> None:
        t0 = time.time()
        t0m = time.monotonic()      # exec histogram: step-immune clock
        tctx = self._open_exec_span(spec)
        self._record_event(spec.task_id, spec.name, "EXEC_STARTED")
        try:
            result = self._invoke_actor_method(spec)
            error = False
        except BaseException as e:  # noqa: BLE001
            result = TaskError(e, format_exception(e), task_name=spec.name)
            error = True
        tr = self._close_exec_span(tctx, spec, error)
        _mp.observe_exec(time.monotonic() - t0m)
        extra = {"name": spec.name}
        if tr is not None:
            extra["_trace"] = tr
        self._send_results(spec.task_id, spec.return_ids, result,
                           spec.num_returns, error, is_actor_task=True,
                           actor_id=spec.actor_id, **extra)
        self._record_event(spec.task_id, spec.name,
                           "EXEC_FAILED" if error else "EXEC_FINISHED",
                           duration_s=time.time() - t0)

    async def _run_actor_task_async(self, spec: ActorTaskSpec) -> None:
        t0 = time.time()
        t0m = time.monotonic()      # exec histogram: step-immune clock
        tctx = self._open_exec_span(spec, set_tls=False)
        self._record_event(spec.task_id, spec.name, "EXEC_STARTED")
        try:
            method = getattr(self._actor, spec.method_name)
            args, kwargs = self._resolve_args(spec.args, spec.kwargs)
            result = await method(*args, **kwargs)
            error = False
        except BaseException as e:  # noqa: BLE001
            result = TaskError(e, format_exception(e), task_name=spec.name)
            error = True
        tr = self._close_exec_span(tctx, spec, error)
        _mp.observe_exec(time.monotonic() - t0m)
        extra = {"name": spec.name}
        if tr is not None:
            extra["_trace"] = tr
        self._send_results(spec.task_id, spec.return_ids, result,
                           spec.num_returns, error, is_actor_task=True,
                           actor_id=spec.actor_id, **extra)
        self._record_event(spec.task_id, spec.name,
                           "EXEC_FAILED" if error else "EXEC_FINISHED",
                           duration_s=time.time() - t0)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--addr", required=True)
    parser.add_argument("--worker-id", required=True)
    args = parser.parse_args()
    host, port = args.addr.rsplit(":", 1)

    executor_box: dict = {}

    def handler(conn, msg):
        executor_box["exec"].handle(conn, msg)

    def on_close(conn):
        # Driver went away: nothing useful left to do.
        os._exit(0)

    _tp.set_role("worker", args.worker_id)
    conn = protocol.connect((host, int(port)), handler, on_close,
                            name=f"worker-{args.worker_id}")
    # the worker is a hot emitter (TASK_DONE bursts, decref floods):
    # coalesce its fire-and-forget frames
    conn.enable_coalescing()
    ctx = WorkerContext(conn, args.worker_id)
    _context.set_ctx(ctx)
    executor = WorkerExecutor(ctx)
    executor_box["exec"] = executor
    direct_port = executor.start_direct_server()
    from ray_tpu import native as _native
    conn.send({"type": protocol.REGISTER, "worker_id": args.worker_id,
               "pid": os.getpid(),
               # which wire engine this worker runs (native frame
               # pump/codec vs pure Python) — lets the head spot
               # mixed-mode fleets when debugging perf regressions
               "wire_native": _native.frame_engine_enabled(),
               # r18: this worker's direct-call serving port (None
               # when the plane is off) — resolves as the actor's
               # endpoint once the head learns it
               "direct_port": direct_port})
    executor.stop_event.wait()
    executor.flush_events()
    try:
        conn.flush()             # drain any coalescing-queued frames
    except protocol.ConnectionClosed:
        pass
    conn.close()
    # Daemonic pool threads may be mid-task; hard-exit like the reference's
    # worker does on graceful shutdown after draining.
    os._exit(0)


if __name__ == "__main__":
    main()
