"""Pubsub: cursor-based channels on the control plane.

Parity: reference src/ray/pubsub (long-poll publisher/subscriber used
for actor/node/error channels) — re-shaped for this topology: the
driver-resident `Publisher` keeps a bounded ring per channel; consumers
poll with a cursor (workers via the STATE_OP RPC, driver-side readers
directly), which gives the same at-least-once-in-order contract the
reference's long-poll delivers without a push socket per subscriber.

Wired publications: node lifecycle (cluster) and actor lifecycle
(controller) — the channels the reference's GCS publishes.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# Well-known channels (reference rpc::ChannelType)
NODE_CHANNEL = "node"
ACTOR_CHANNEL = "actor"
ERROR_CHANNEL = "error"


class StaleCursorError(Exception):
    """The cursor predates the retained window: messages were evicted
    and are unrecoverable (the caller must resync its view)."""


class Publisher:
    def __init__(self, maxlen_per_channel: int = 1000):
        self._lock = threading.Condition()
        self._maxlen = maxlen_per_channel
        # channel -> (next_seq, ring of (seq, ts, message))
        self._channels: Dict[str, Tuple[int, deque]] = {}

    def publish(self, channel: str, message: Any) -> int:
        with self._lock:
            seq, ring = self._channels.get(channel, (0, None))
            if ring is None:
                ring = deque(maxlen=self._maxlen)
            ring.append((seq, time.time(), message))
            self._channels[channel] = (seq + 1, ring)
            self._lock.notify_all()
            return seq

    def poll(self, channel: str, cursor: int = 0,
             timeout: Optional[float] = None
             ) -> Tuple[List[Any], int]:
        """Messages with seq >= cursor and the next cursor. With a
        timeout, blocks until at least one message lands (long-poll)."""
        deadline = None if timeout is None else time.time() + timeout

        def fetch():
            seq, ring = self._channels.get(channel, (0, None))
            if ring is None:
                return [], 0
            if ring and cursor < ring[0][0]:
                # at-least-once contract: never silently skip evicted
                # messages — the subscriber fell too far behind
                raise StaleCursorError(
                    f"channel {channel!r}: cursor {cursor} predates "
                    f"oldest retained seq {ring[0][0]}")
            msgs = [(s, m) for s, _, m in ring if s >= cursor]
            return msgs, seq

        with self._lock:
            msgs, next_cursor = fetch()
            while not msgs and deadline is not None:
                left = deadline - time.time()
                if left <= 0:
                    break
                self._lock.wait(timeout=min(left, 0.25))
                msgs, next_cursor = fetch()
            return [m for _, m in msgs], max(next_cursor, cursor)

    def channels(self) -> List[str]:
        with self._lock:
            return sorted(self._channels)
