"""Pull manager: deduped, bounded, multi-source object transfers.

Reference src/ray/object_manager/pull_manager.cc: every endpoint that
fetches remote objects (each node agent, and the head) runs one of
these in front of the raw chunked pull protocol. It provides:

- **Dedup**: concurrent requests for the same object join one in-flight
  transfer instead of each opening a session (two getters, one
  transfer; counted as ``pull_dedup_hits``).
- **Bounds**: at most ``pull_concurrency`` transfers run at once, and
  their admitted sizes share a ``pull_max_inflight_bytes`` budget —
  a node pulling many large objects cannot balloon its memory.
- **Multi-source**: sources come from the cluster object directory
  (every registered holder, not just the original producer), tried in
  preference order with failover; a source that no longer holds the
  object is reported back so the directory drops the stale location.
- **Retry**: chunk-level drops retry within a source (see
  ``pull_object``); source-level failures rotate to the next holder.

The manager is transport-agnostic: callers supply ``sources_fn`` which
yields ``(source_id, connection)`` pairs for an object (the agent backs
it with LOCATE_OBJECT + lazy peer dials; the head with the directory +
its agent control connections), and ``on_complete`` /
``on_source_failed`` hooks for replica registration and stale-location
teardown.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG
from ray_tpu._private.object_transfer import (OBJECT_PLANE_STATS,
                                              PullBudgetExceeded,
                                              StoredObject, pull_object)


class ByteBudget:
    """Shared in-flight byte accounting. ``reserve`` blocks until the
    transfer fits (or it is the only one — a single object larger than
    the whole budget must still be admissible)."""

    def __init__(self, cap: int):
        self.cap = cap
        self.used = 0
        self.active = 0
        self._cv = threading.Condition()

    def reserve(self, n: int, timeout: Optional[float] = None) -> bool:
        if self.cap <= 0:
            return True
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cv:
            while not (self.used + n <= self.cap or self.active == 0):
                left = (None if deadline is None
                        else deadline - time.monotonic())
                if left is not None and left <= 0:
                    return False
                self._cv.wait(timeout=left)
            self.used += n
            self.active += 1
            return True

    def release(self, n: int) -> None:
        if self.cap <= 0:
            return
        with self._cv:
            self.used -= n
            self.active -= 1
            self._cv.notify_all()


class _Flight:
    __slots__ = ("event", "result")

    def __init__(self):
        self.event = threading.Event()
        self.result: Optional[StoredObject] = None


class PullManager:
    def __init__(self, store,
                 sources_fn: Callable[[str, Optional[dict]],
                                      Iterable[tuple]],
                 on_complete: Optional[Callable] = None,
                 on_source_failed: Optional[Callable] = None,
                 on_partial: Optional[Callable] = None,
                 on_partial_failed: Optional[Callable] = None,
                 deprioritize_fn: Optional[Callable[[Any], bool]] = None,
                 name: str = ""):
        self._store = store
        self._sources_fn = sources_fn
        # r17 suspicion: `deprioritize_fn(source_id)` -> True moves a
        # holder to the END of the rotation (tried only after every
        # healthy holder failed). The head backs it with the cluster's
        # SUSPECT flag so a gray-failing node stops being the first
        # source a transfer gambles its deadline on.
        self._deprioritize = deprioritize_fn
        self._on_complete = on_complete
        self._on_source_failed = on_source_failed
        # cut-through hooks (r12): `on_partial(object_id, nbytes)`
        # fires once per winning transfer at its FIRST landed chunk —
        # the partial-holder directory registration that unlocks the
        # node's broadcast subtree while the pull is still in flight.
        # `on_partial_failed(object_id)` retracts it when the transfer
        # dies after registering (children fall back multi-source).
        self._on_partial = on_partial
        self._on_partial_failed = on_partial_failed
        self.name = name
        self._lock = threading.Lock()
        self._inflight: dict[str, _Flight] = {}
        self._sem = threading.Semaphore(max(1, _CFG.pull_concurrency))
        self._budget = ByteBudget(_CFG.pull_max_inflight_bytes)

    # ------------------------------------------------------------ api
    def pull(self, object_id: str, prefer: Optional[dict] = None,
             timeout: Optional[float] = 60.0,
             trace_ctx: Optional[tuple] = None) -> Optional[StoredObject]:
        """Fetch `object_id` into the local store and return it (None
        on timeout/no-source). Concurrent calls for one object share a
        single transfer; `prefer` (an opaque source hint passed through
        to sources_fn, e.g. a broadcast parent) is honored by the
        winning transfer only. `trace_ctx` — an explicit
        (trace_id, parent_span), else the calling thread's current —
        puts the transfer on the tracing-plane timeline: the winner
        records one "pull" span and stamps the PULL_OBJECT message so
        the holder's serve span parents under it (joiners record
        nothing; they did no transfer work)."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        stored = self._store.get_stored(object_id, timeout=0)
        if stored is not None:
            return stored
        with self._lock:
            flight = self._inflight.get(object_id)
            if flight is not None:
                joiner = True
                OBJECT_PLANE_STATS["pull_dedup_hits"] += 1
            else:
                joiner = False
                flight = self._inflight[object_id] = _Flight()
        if joiner:
            flight.event.wait(None if deadline is None
                              else max(0.0, deadline - time.monotonic()))
            if flight.result is not None:
                return flight.result
            # winner failed or we timed out: one local re-probe (the
            # object may have sealed locally through another path)
            return self._store.get_stored(object_id, timeout=0)
        try:
            with _tp.span("pull", "pull:" + object_id[:16],
                          ctx=trace_ctx):
                flight.result = self._transfer(object_id, prefer,
                                               deadline)
        finally:
            with self._lock:
                self._inflight.pop(object_id, None)
            flight.event.set()
        return flight.result

    def _transfer(self, object_id: str, prefer: Optional[dict],
                  deadline: Optional[float]) -> Optional[StoredObject]:
        OBJECT_PLANE_STATS["pulls_started"] += 1
        acquired = self._sem.acquire(
            timeout=None if deadline is None
            else max(0.0, deadline - time.monotonic()))
        if not acquired:
            OBJECT_PLANE_STATS["pulls_failed"] += 1
            return None
        partial_fired = {"v": False}

        def _first_chunk(nbytes: int) -> None:
            # winner-only, once per transfer: register this node as a
            # PARTIAL holder so the broadcast coordinator dispatches
            # our subtree against the landing (cut-through)
            if partial_fired["v"] or not _CFG.pull_cut_through:
                return
            partial_fired["v"] = True
            if self._on_partial is not None:
                try:
                    self._on_partial(object_id, nbytes)
                except Exception:
                    pass

        try:
            stored = self._store.get_stored(object_id, timeout=0)
            if stored is not None:      # landed while we queued
                return stored
            for source_id, conn in self._iter_sources(object_id, prefer):
                if conn is None or getattr(conn, "closed", False):
                    continue
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                try:
                    stored = pull_object(conn, object_id,
                                         timeout=remaining,
                                         budget=self._budget,
                                         store=self._store,
                                         on_first_chunk=_first_chunk)
                except PullBudgetExceeded:
                    # our own admission control, not the source's
                    # fault: keep the location, and stop rotating —
                    # every other source hits the same budget wall
                    # (each attempt would strand another pinned
                    # encoded blob on a holder until its TTL)
                    break
                except TimeoutError:
                    # the CALLER's deadline expired mid-transfer, not
                    # the holder failing: reporting this as a source
                    # failure would deregister a valid copy cluster-
                    # wide (and trigger spurious lineage re-execution)
                    break
                except protocol.ConnectionClosed:
                    stored = None
                if stored is not None:
                    OBJECT_PLANE_STATS["pulls_completed"] += 1
                    # the manifest land path sealed into the store
                    # itself (closing the landing->store serve gap);
                    # only the blob path still needs the put here.
                    # contains() is a residency probe (spilled counts):
                    # get_stored would synchronously restore a copy
                    # the LRU pass just spilled, on this thread
                    if not self._store.contains(object_id):
                        OBJECT_PLANE_STATS["pull_bytes"] += stored.nbytes
                        self._store.put_stored(stored)
                    if self._on_complete is not None:
                        try:
                            self._on_complete(object_id, stored,
                                              source_id)
                        except Exception:
                            pass
                    return stored
                if self._on_source_failed is not None:
                    try:
                        self._on_source_failed(object_id, source_id)
                    except Exception:
                        pass
            OBJECT_PLANE_STATS["pulls_failed"] += 1
            return None
        finally:
            self._sem.release()
            if partial_fired["v"] and self._on_partial_failed is not None:
                # the transfer registered as a partial holder but never
                # completed (this thread is leaving without a store
                # copy): retract the advisory location. Residency
                # probe, NOT get_stored: a sealed-then-spilled copy is
                # still held (retracting would drop the FULL location)
                # and must not cost a synchronous disk restore here
                if not self._store.contains(object_id):
                    try:
                        self._on_partial_failed(object_id)
                    except Exception:
                        pass

    def _iter_sources(self, object_id: str, prefer: Optional[dict]):
        """The caller's source rotation with suspect holders deferred
        to the tail (r17): lazily forwarded when no deprioritize hook
        is installed, so the agent-side lazy peer dials keep their
        one-dial-per-yield behavior."""
        if self._deprioritize is None:
            yield from self._sources_fn(object_id, prefer)
            return
        deferred = []
        for src in self._sources_fn(object_id, prefer):
            try:
                suspect = bool(self._deprioritize(src[0]))
            except Exception:
                suspect = False
            if suspect:
                OBJECT_PLANE_STATS["pull_suspect_deferred"] += 1
                deferred.append(src)
            else:
                yield src
        yield from deferred

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def stats(self) -> dict:
        return {"inflight": self.inflight(),
                "inflight_bytes": self._budget.used,
                "budget_bytes": self._budget.cap}
