"""Object plane: serialization + shared-memory object store.

Replaces the reference's two-tier object plane (in-process memory store,
reference src/ray/core_worker/store_provider/memory_store/memory_store.h:43,
and the plasma shm arena, reference src/ray/object_manager/plasma/) with:

- ``serialize``/``deserialize`` built on pickle protocol 5 with
  ``buffer_callback``: large contiguous buffers (numpy / jax host arrays)
  are carved out-of-band so cross-process transfer is zero-copy through
  POSIX shared memory, the same property plasma's fd-passing provides
  (reference plasma/fling.cc) without a bespoke arena: the kernel shm
  object *is* the arena and the eviction unit.
- ``LocalStore``: the driver-resident authoritative store. Small payloads
  live inline; each large buffer lives in its own named shm segment,
  unlinked when the distributed refcount hits zero (refcounting lives in
  the controller, reference core_worker/reference_count.cc analogue).

Lifetime design: a segment exists *by name* in the kernel from creation
until ``shm_unlink``; no process needs to hold a handle to keep it alive.
Creators therefore write, then immediately close the fd. Readers map via
raw ``mmap`` (not SharedMemory, which would leak an fd per attach); the
mapping is freed automatically when the last deserialized array view is
garbage collected. Unlink-while-mapped is safe POSIX: existing mappings
survive, the name disappears.

Segment pooling (``_SegmentPool``): refcount-zero releases feed a
bounded size-classed free pool (segments renamed, not unlinked) that
the next compatible ``put`` reuses, eliminating the per-put
create/zero-fill/fault/unlink churn on the large-object path;
``RAY_TPU_SHM_POOL=0`` restores strict unlink-on-free. Reuse is only
sound because nothing can still be mapping a pooled segment's pages:
deserialized views hold a borrow on their object until collected
(``_pin_mapped_object``), so the refcount cannot hit zero under them;
transient copiers (pull serving) mark their names via
``guard_segments``; and every release site that can run with live
refs (spill, stale re-put) keeps the mapping-safe plain unlink.
"""
from __future__ import annotations

import collections
import mmap
import os
import pickle
import sys
import threading
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Any, Optional

import _posixshmem  # CPython's shm syscall wrapper (used by SharedMemory)
import cloudpickle

# Buffers below this many bytes ride inline in the pickled payload; larger
# ones are carved into shm segments. Mirrors the reference's inline-small
# -return threshold semantics (task returns under ~100KiB go to the owner's
# memory store; reference core_worker.h AllocateReturnObject).
from ray_tpu._private.config import CONFIG as _CFG


def _local_tag() -> str:
    """Segment names carry the PRODUCING process tree's session tag
    (not the id-issuer's): a task submitted by a remote driver but
    executed here seals segments on THIS host, and this host's
    tag-prefixed sweep must find them."""
    from ray_tpu._private.specs import SESSION_TAG
    return SESSION_TAG


def new_object_id() -> str:
    from ray_tpu._private.specs import SESSION_TAG, rand_hex
    return SESSION_TAG + rand_hex(14)


@dataclass
class StoredObject:
    """Serialized object: inline payload + optional out-of-band shm buffers."""
    object_id: str
    payload: bytes                      # pickle5 stream (buffers external)
    inline_buffers: list[bytes] = field(default_factory=list)
    shm_names: list[str] = field(default_factory=list)
    shm_sizes: list[int] = field(default_factory=list)
    buffer_order: list[str] = field(default_factory=list)  # "i" inline / "s" shm
    is_error: bool = False              # payload deserializes to an exception
    # object ids of refs pickled INSIDE this value: the controller holds
    # a count on each until this object is deleted (nested-ref ownership,
    # reference reference_count.cc)
    contained_ids: list[str] = field(default_factory=list)
    # kernel bytes actually backing each shm segment (pool class-
    # rounding makes this larger than shm_sizes): what capacity/spill
    # ledgers must charge, while shm_sizes stays the mmap data length.
    # Empty for pre-pool producers -> nbytes falls back to shm_sizes.
    shm_alloc_sizes: list[int] = field(default_factory=list)

    @property
    def nbytes(self) -> int:
        # getattr: a StoredObject pickled by a pre-pool peer restores
        # without the field (pickle bypasses __init__)
        alloc = getattr(self, "shm_alloc_sizes", None)
        return (len(self.payload) + sum(len(b) for b in self.inline_buffers)
                + sum(alloc or self.shm_sizes))


class _SegmentPool:
    """Size-classed free pool of shm segments (reference plasma keeps
    its arena mapped for the same reason: creating + faulting fresh
    kernel pages per large put dominates the copy itself).

    A freed segment is RENAMED (atomic on the /dev/shm tmpfs) into a
    bounded per-class free list instead of unlinked; the next put of a
    compatible size renames it back to its object name and overwrites
    it — skipping shm_open(O_CREAT)/ftruncate and, far more
    importantly, the page-zeroing + soft-fault cost of first touch.
    Pool names carry the session tag (``rtpu_<tag>_pool...``), so the
    existing unlink-by-name lifetime rules still apply: pool overflow
    falls back to a plain unlink, and the session shutdown sweep reaps
    anything still pooled. Per-process: the driver (which frees most
    result segments) feeds its own next puts."""

    MIN_CLASS = 17          # 128 KiB: below that, buffers ride inline

    def __init__(self):
        self._classes: dict[int, "collections.deque[str]"] = {}
        self._lock = threading.Lock()
        self._bytes = 0
        self.reused = 0     # introspection / bench counters
        self.pooled = 0
        self.misses = 0     # eligible acquires the pool couldn't serve

    @staticmethod
    def _cls(nbytes: int) -> int:
        return max((nbytes - 1).bit_length(), _SegmentPool.MIN_CLASS)

    @staticmethod
    def class_size(nbytes: int) -> int:
        return 1 << _SegmentPool._cls(nbytes)

    def _enabled(self) -> bool:
        return _CFG.shm_pool and os.path.isdir("/dev/shm")

    def acquire(self, name: str, data_len: int) -> bool:
        """Rename a pooled segment of the right class to `name`.
        False when the pool has nothing compatible (caller creates)."""
        if not self._enabled():
            return False
        cls = self._cls(data_len)
        with self._lock:
            free = self._classes.get(cls)
            if not free:
                self.misses += 1
                return False
            pooled_name = free.popleft()
            self._bytes -= 1 << cls
        try:
            os.rename("/dev/shm/" + pooled_name, "/dev/shm/" + name)
        except OSError:
            # pooled entry vanished (external sweep): just miss
            self.misses += 1
            return False
        self.reused += 1
        return True

    def release(self, name: str) -> bool:
        """Take ownership of a freed segment: rename it into the pool.
        False -> not pooled (wrong shape / over budget / disabled /
        mid-copy in this process); the caller must unlink it as
        before."""
        if not self._enabled():
            return False
        path = "/dev/shm/" + name
        try:
            size = os.stat(path).st_size
        except OSError:
            return False
        # only class-shaped segments are reusable (pre-pool segments
        # have exact data sizes; renaming those would strand capacity)
        if size < (1 << self.MIN_CLASS) or size & (size - 1):
            return False
        cls = size.bit_length() - 1
        with self._lock:
            # a transient copier (pull serve, guard_segments) is mid-
            # map: renaming + reusing would overwrite the pages under
            # its copy — fall back to the unlink path, which existing
            # mappings survive. The guard registers and the rename
            # happens under the same lock, so there is no window where
            # a fresh guard can race an in-flight rename.
            if name in _guarded_segments:
                return False
            free = self._classes.setdefault(cls, collections.deque())
            if (len(free) >= _CFG.shm_pool_per_class
                    or self._bytes + size > _CFG.shm_pool_max_bytes):
                return False
            pooled_name = (f"rtpu_{_local_tag()}_pool{cls:02d}_"
                           f"{uuid.uuid4().hex[:8]}")
            try:
                os.rename(path, "/dev/shm/" + pooled_name)
            except OSError:
                return False
            free.append(pooled_name)
            self._bytes += size
        self.pooled += 1
        return True

    def clear(self) -> int:
        """Unlink everything pooled (store shutdown)."""
        with self._lock:
            names = [n for free in self._classes.values() for n in free]
            self._classes.clear()
            self._bytes = 0
        for name in names:
            unlink_segment(name)
        return len(names)

    def stats(self) -> dict:
        with self._lock:
            return {"pool_bytes": self._bytes,
                    "pool_segments": sum(len(f) for f in
                                         self._classes.values()),
                    "pool_reused": self.reused,
                    "pool_misses": self.misses,
                    "pool_released": self.pooled,
                    # riding the pool surface into /metrics: the put
                    # path's user-space byte copies (r12 copy budget)
                    "put_bytes_copied": COPY_STATS["put_bytes_copied"]}


SEGMENT_POOL = _SegmentPool()

# Segment names a transient copier in THIS process is currently
# mapping (pull-serve materialize): free_segment must not pool these —
# reuse would overwrite the pages mid-copy, where plain unlink is
# harmless. Guarded by SEGMENT_POOL._lock (see release()).
_guarded_segments: collections.Counter = collections.Counter()


class guard_segments:
    """Context manager marking `names` as mapped-for-copy so a
    concurrent refcount-zero free in this process unlinks instead of
    pooling them (preserving the pages under the copy)."""

    def __init__(self, names):
        self._names = list(names)

    def __enter__(self):
        with SEGMENT_POOL._lock:
            _guarded_segments.update(self._names)
        return self

    def __exit__(self, *exc):
        with SEGMENT_POOL._lock:
            _guarded_segments.subtract(self._names)
            for n in self._names:
                if _guarded_segments[n] <= 0:
                    del _guarded_segments[n]
        return False


def free_segment(name: str) -> None:
    """Refcount-zero release path: pool the segment for reuse when
    possible, else unlink-by-name exactly as before. Only safe for
    segments with no established mappings — i.e. the refcount-zero
    delete path, where the deserialize-time borrow pin
    (_pin_mapped_object) guarantees no live views remain; every other
    release site (spill, stale re-put) must keep unlink_segment."""
    if not SEGMENT_POOL.release(name):
        unlink_segment(name)


# Copy accounting for the zero-copy envelope (r12): every user-space
# byte copy on the put path (serialize -> shm) bumps this, so the
# bytes-copied-per-byte-transferred bench columns and the metrics
# plane (ray_tpu_shm_pool{counter="put_bytes_copied"}) can prove copy
# regressions. Plain int increment under the GIL, WIRE_STATS
# discipline. Transfer-side copies live in OBJECT_PLANE_STATS.
COPY_STATS = {"put_bytes_copied": 0}


def bulk_copy(dst, dst_off: int, src) -> int:
    """Copy `src` (any contiguous buffer) into the writable buffer
    `dst` at `dst_off` — through the native GIL-released memcpy when
    the library is loadable, else a plain slice assign. Returns bytes
    copied. The single choke point for object-plane byte copies, so
    the copy counters cannot drift from the copies."""
    from ray_tpu import native as _native
    n = src.nbytes if isinstance(src, memoryview) else len(src)
    if n >= 65536 and _native.available():
        _native.buf_copy(dst, dst_off, src)
    else:
        dst[dst_off:dst_off + n] = src
    return n


def _open_segment_for_write(name: str, n: int) -> tuple:
    """Create (or reuse from the pool) a named segment sized for `n`
    data bytes and return ``(mmap, alloc_size)`` with the mapping left
    OPEN for the caller to fill; the segment persists by name until
    shm_unlink. Fresh segments are rounded up to the pool's size class
    so they are poolable when freed (readers map only the data length;
    mapping a prefix of the file is fine). alloc_size is the allocated
    kernel size — the class-rounded figure capacity ledgers must
    charge (a reused segment's already-touched pages can span its
    whole class regardless of this object's data length)."""
    size = SEGMENT_POOL.class_size(n) if SEGMENT_POOL._enabled() else n
    if SEGMENT_POOL.acquire(name, n):
        try:
            fd = _posixshmem.shm_open("/" + name, os.O_RDWR, mode=0o600)
            try:
                mm = mmap.mmap(fd, n)
            finally:
                os.close(fd)
            return mm, size
        except (OSError, ValueError):
            # reused segment unusable after all: fall through to create
            unlink_segment(name)
    flags = os.O_CREAT | os.O_EXCL | os.O_RDWR
    try:
        fd = _posixshmem.shm_open("/" + name, flags, mode=0o600)
    except FileExistsError:
        # Stale segment from a killed process re-running the same task
        # (lineage resubmission re-uses the object id, and same-host
        # node agents share /dev/shm). The name encodes the producing
        # task, so reclaiming is safe.
        unlink_segment(name)
        fd = _posixshmem.shm_open("/" + name, flags, mode=0o600)
    try:
        os.ftruncate(fd, size)
        mm = mmap.mmap(fd, n)
    finally:
        os.close(fd)
    return mm, size


def _create_segment(name: str, data: memoryview) -> int:
    """Create (or reuse) + fill a named segment in one step — the
    serialize() path. One memcpy total (pickle5's buffer_callback
    hands over zero-copy views of the source arrays), GIL-released
    through the native core for large buffers. Returns the allocated
    kernel size (see _open_segment_for_write)."""
    n = len(data)
    mm, size = _open_segment_for_write(name, n)
    COPY_STATS["put_bytes_copied"] += bulk_copy(mm, 0, data)
    mm.close()
    return size


def _map_segment(name: str, size: int) -> memoryview:
    """Map an existing segment read-write; the fd is closed immediately so
    nothing leaks — the mmap lives as long as views into it do."""
    fd = _posixshmem.shm_open("/" + name, os.O_RDWR, mode=0o600)
    try:
        mm = mmap.mmap(fd, size)
    finally:
        os.close(fd)
    return memoryview(mm)[:size]


def reap_object_segments(object_id: str, max_buffers: int = 64) -> int:
    """Unlink shm segments a dead producer may have created for
    `object_id` before its TASK_DONE reached us (worker killed between
    serialize and send). Buffer indices may have gaps (small buffers
    store inline), so scan /dev/shm for the prefix rather than probing
    sequentially. Returns the number reaped."""
    reaped = 0
    prefix = f"rtpu_{_local_tag()}_{object_id}_"
    try:
        names = [n for n in os.listdir("/dev/shm")
                 if n.startswith(prefix)]
    except OSError:
        # no listable shm dir (non-Linux): fall back to index probing
        # over the full range, tolerating gaps
        names = [f"rtpu_{_local_tag()}_{object_id}_{i}"
                 for i in range(max_buffers)]
    for name in names:
        try:
            _posixshmem.shm_unlink("/" + name)
            reaped += 1
        except OSError:
            pass
    return reaped


def sweep_session_segments() -> int:
    """Unlink every shm segment created under THIS process tree's
    session tag (ids embed it, so segment names start with
    rtpu_<tag>). Safe only once all of the session's producers and
    consumers are stopped — called from Runtime/NodeAgent shutdown."""
    from ray_tpu._private.specs import SESSION_TAG
    # the trailing separator matters: tag "abcd" must never match a
    # concurrent session's "abcd12..." segments (every segment name is
    # rtpu_<producer-tag>_<rest>)
    prefix = f"rtpu_{SESSION_TAG}_"
    reaped = 0
    try:
        names = os.listdir("/dev/shm")
    except OSError:
        return 0
    for name in names:
        if name.startswith(prefix):
            try:
                _posixshmem.shm_unlink("/" + name)
                reaped += 1
            except OSError:
                pass
    return reaped


def unlink_segment(name: str) -> None:
    try:
        _posixshmem.shm_unlink("/" + name)
    except FileNotFoundError:
        pass
    except OSError:
        pass


def serialize(value: Any, object_id: Optional[str] = None,
              create_shm: bool = True) -> StoredObject:
    object_id = object_id or new_object_id()
    raw_buffers: list[pickle.PickleBuffer] = []
    from ray_tpu._private.refs import _capture
    # Save/restore the enclosing capture list instead of resetting to
    # None: a nested serialize (user __reduce__ calling put() mid-
    # pickle) must not stop the OUTER object's later refs from
    # registering as contained — they would be deletable while still
    # referenced.
    prev_ids = getattr(_capture, "ids", None)
    _capture.ids = contained = []
    try:
        payload = cloudpickle.dumps(value, protocol=5,
                                    buffer_callback=raw_buffers.append)
    finally:
        _capture.ids = prev_ids
    inline: list[bytes] = []
    shm_names: list[str] = []
    shm_sizes: list[int] = []
    shm_alloc: list[int] = []
    order: list[str] = []
    for i, pb in enumerate(raw_buffers):
        mv = pb.raw()
        if len(mv) < _CFG.inline_threshold_bytes or not create_shm:
            inline.append(mv.tobytes())
            order.append("i")
        else:
            name = f"rtpu_{_local_tag()}_{object_id}_{i}"
            shm_alloc.append(_create_segment(name, mv))
            shm_names.append(name)
            shm_sizes.append(len(mv))
            order.append("s")
    is_error = isinstance(value, BaseException)
    return StoredObject(object_id, payload, inline, shm_names, shm_sizes,
                        order, is_error, contained_ids=contained,
                        shm_alloc_sizes=shm_alloc)


def _pin_mapped_object(object_id: str, mms: list) -> None:
    """Hold a borrow on `object_id` while any of the given mmaps is
    alive. Unlink-by-name made freeing at refcount zero safe for
    already-established mappings (the pages survived); pooled reuse
    does not — the next put OVERWRITES them. So a deserialized view
    must keep the refcount above zero until it is collected: addref
    now, deferred decref when the last mmap dies (same discipline as
    ObjectRef.__del__ — never decref synchronously from a finalizer)."""
    if not SEGMENT_POOL._enabled():
        return                      # unlink-on-free: seed semantics
    from ray_tpu._private import context as _context
    from ray_tpu._private import refs as _refs
    ctx = _context.maybe_ctx()
    if ctx is None:
        return
    try:
        ctx.addref(object_id)
    except Exception:
        return
    tokens: "collections.deque[int]" = collections.deque(range(len(mms)))

    def _release(_tokens=tokens, _oid=object_id):
        _tokens.popleft()           # deque ops are GC-reentrancy-safe
        if not _tokens:
            _refs._deferred.append(_oid)
            _refs._flush_wake.set()
            _refs._ensure_flusher()

    for mm in mms:
        weakref.finalize(mm, _release)


def deserialize(obj: StoredObject) -> Any:
    """Reconstruct the value. shm-backed buffers become zero-copy views
    whose underlying mappings are freed when the views are collected;
    while any view lives, the object is pinned (see
    _pin_mapped_object) so the segment pool cannot reuse its pages."""
    buffers: list[Any] = []
    mms: list[Any] = []
    ii = si = 0
    for kind in obj.buffer_order:
        if kind == "i":
            buffers.append(obj.inline_buffers[ii]); ii += 1
        else:
            mv = _map_segment(obj.shm_names[si], obj.shm_sizes[si])
            buffers.append(mv)
            mms.append(mv.obj)      # the underlying mmap
            si += 1
    if mms:
        _pin_mapped_object(obj.object_id, mms)
    return pickle.loads(obj.payload, buffers=buffers)


@dataclass
class _SpilledObject:
    object_id: str
    path: str
    nbytes: int


class LocalStore:
    """Driver-resident object store: refcount-driven deletion, plus a
    capacity cap with LRU spill-to-disk of unpinned objects.

    Parity: reference plasma eviction
    (object_manager/plasma/eviction_policy.cc LRU) + raylet spilling
    (raylet/local_object_manager.cc). A `put` that pushes residency past
    `capacity_bytes` spills least-recently-used unpinned objects to
    `spill_dir` (shm segments are materialised into the spill file and
    unlinked); a later `get` restores transparently.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 spill_dir: Optional[str] = None,
                 pinned_fn=None):
        import collections
        import tempfile
        if capacity_bytes is None:
            capacity_bytes = _CFG.object_store_memory or None
        self.capacity_bytes = capacity_bytes
        self._spill_dir = spill_dir or os.path.join(
            tempfile.gettempdir(), f"rtpu_spill_{os.getpid()}")
        self._pinned_fn = pinned_fn or (lambda: ())
        self._objects: "collections.OrderedDict[str, StoredObject]" = (
            collections.OrderedDict())
        self._spilled: dict[str, _SpilledObject] = {}
        # last hand-out time per object: the spill policy avoids objects
        # a reader may be about to map (get_stored returns shm names the
        # caller maps OUTSIDE the lock; see _pick_victims_locked)
        self._touched_at: dict[str, float] = {}
        self._spilling: set[str] = set()        # popped, disk write in flight
        self._spill_cancelled: set[str] = set()  # deleted mid-spill
        self._restoring: set[str] = set()        # spill-file read in flight
        self._restore_cancelled: set[str] = set()  # deleted mid-restore
        # process-local pins (pull sessions serving an object): the
        # spill policy must not evict these mid-transfer. Orthogonal to
        # the distributed pin set (pinned_fn) the head computes.
        self._local_pins: "collections.Counter[str]" = (
            collections.Counter())
        self._bytes = 0
        self._spilled_bytes_total = 0
        self._restored_bytes_total = 0
        from ray_tpu._private.debug_sync import make_lock
        self._lock = make_lock("object_store")
        self._cv = threading.Condition(self._lock)
        # Seal hook: called AFTER an object lands (outside the lock)
        # with its id — the runtime's waiter registry resolves blocked
        # gets/waits on it (event-driven, no parked threads).
        self.on_seal = None

    # ------------------------------------------------------------- put
    def put_stored(self, obj: StoredObject, block: bool = False) -> None:
        """Admit a sealed object. ``block=True`` applies create-queueing
        backpressure when the store is over cap and fully pinned — ONLY
        safe on producer-owned threads (driver put); connection reader
        threads must pass False (blocking them stalls the very messages
        whose processing releases pins) and instead forward the
        ``over_capacity()`` hint to the producer."""
        stale: list[str] = []
        with self._cv:
            old = self._objects.pop(obj.object_id, None)
            if old is not None:
                self._bytes -= old.nbytes
                # re-stored id (task retry): reclaim segments the new
                # object doesn't reuse, or they outlive the process
                stale = [n for n in old.shm_names
                         if n not in set(obj.shm_names)]
            self._objects[obj.object_id] = obj
            self._bytes += obj.nbytes
            self._touched_at[obj.object_id] = time.monotonic()
            victims = self._pick_victims_locked()
            self._cv.notify_all()
        for name in stale:
            # NOT free_segment: the replaced incarnation may still be
            # mapped by readers (the id is live — this is a re-put);
            # unlink keeps their pages intact, pooling would not
            unlink_segment(name)
        self._write_spills(victims)
        # Seal BEFORE any backpressure wait: consumers blocked on this
        # object must resolve (their tasks finishing is what releases
        # the pins that free space — delaying the seal would deadlock
        # the very backpressure loop).
        if self.on_seal is not None:
            self.on_seal(obj.object_id)
        if block:
            self._put_backpressure()

    def over_capacity(self) -> bool:
        """Still over cap after the spill pass — i.e. the resident
        overage is pinned. Producers use this as a throttle hint."""
        with self._lock:
            return (self.capacity_bytes is not None
                    and self._bytes > self.capacity_bytes)

    def _put_backpressure(self) -> None:
        """Create-queueing parity (reference plasma
        create_request_queue.cc): when the store is over capacity and
        nothing is spillable — every resident byte pinned by in-flight
        work — park the PRODUCER until space frees (deletes, unpins
        making spill possible) or the budget runs out, then admit
        over-cap with a loud warning instead of failing."""
        if self.capacity_bytes is None:
            return
        block_s = _CFG.store_put_block_s
        if block_s <= 0:
            return
        deadline = time.monotonic() + block_s
        warned_wait = False
        while True:
            with self._cv:
                if self._bytes <= self.capacity_bytes:
                    return
                victims = self._pick_victims_locked()
                if not victims:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        sys.stderr.write(
                            f"ray_tpu: object store over capacity "
                            f"({self._bytes} > {self.capacity_bytes} "
                            f"bytes) with all bytes pinned by in-flight "
                            f"work after {block_s:.0f}s of "
                            f"backpressure; admitting over-cap\n")
                        return
                    if not warned_wait:
                        warned_wait = True
                        sys.stderr.write(
                            "ray_tpu: object store full and fully "
                            "pinned; applying put backpressure\n")
                    self._cv.wait(timeout=min(left, 0.2))
                    continue
            self._write_spills(victims)     # outside the lock

    def put(self, value: Any, object_id: Optional[str] = None,
            block: bool = False) -> str:
        # block defaults False: internal callers (error seals, recovery
        # paths) run on connection reader threads where backpressure
        # would stall the very messages that release pins. Producer-
        # owned threads opt in (Runtime.put).
        obj = serialize(value, object_id)
        self.put_stored(obj, block=block)
        return obj.object_id

    # ----------------------------------------------------------- spill
    def _pick_victims_locked(self) -> list[tuple[str, StoredObject]]:
        """Pop LRU spill victims from residency (lock held) WITHOUT
        doing I/O — the caller writes them to disk after releasing the
        lock (`_write_spills`), so a slow disk never stalls the whole
        object plane. Mid-spill objects are invisible to get/wait until
        recorded; readers simply block on the condvar until then."""
        if self.capacity_bytes is None or self._bytes <= self.capacity_bytes:
            return []
        pinned = set(self._pinned_fn())
        pinned.update(oid for oid, n in self._local_pins.items() if n > 0)
        now = time.monotonic()
        victims: list[tuple[str, StoredObject]] = []

        def take(oid: str) -> None:
            obj = self._objects.pop(oid)
            self._bytes -= obj.nbytes
            self._spilling.add(oid)
            victims.append((oid, obj))

        # LRU order = OrderedDict insertion/touch order. Objects handed
        # out in the last few seconds are skipped: a reader may still be
        # mapping their shm segments outside the lock (get/deserialize
        # race) — the retry path in the runtime covers the remainder.
        deferred: list[str] = []
        for oid in list(self._objects):
            if self._bytes <= self.capacity_bytes:
                break
            if oid in pinned:
                continue
            if now - self._touched_at.get(oid, 0.0) < 5.0:
                deferred.append(oid)
                continue
            take(oid)
        # still over: last resort, take recently-touched (but not
        # pinned) victims rather than blow past the cap unboundedly
        for oid in deferred:
            if self._bytes <= self.capacity_bytes:
                break
            take(oid)
        return victims

    def _write_spills(self, victims: list[tuple[str, StoredObject]]) -> None:
        """Disk I/O phase of spilling (NO store lock held)."""
        if not victims:
            return
        os.makedirs(self._spill_dir, exist_ok=True)
        for oid, obj in victims:
            path = os.path.join(self._spill_dir, oid)
            buffers = []
            ii = si = 0
            for kind in obj.buffer_order:
                if kind == "i":
                    buffers.append(obj.inline_buffers[ii]); ii += 1
                else:
                    mv = _map_segment(obj.shm_names[si], obj.shm_sizes[si])
                    buffers.append(mv.tobytes())
                    del mv
                    si += 1
            with open(path, "wb") as f:
                pickle.dump({"payload": obj.payload, "buffers": buffers,
                             "is_error": obj.is_error,
                             "contained": obj.contained_ids}, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            for name in obj.shm_names:
                # NOT free_segment: spill victims usually have live
                # refs, so readers may hold mapped views of these
                # segments; unlink preserves their pages, pooled reuse
                # would overwrite them
                unlink_segment(name)
            with self._cv:
                self._spilling.discard(oid)
                if oid in self._spill_cancelled:
                    # deleted while we were writing: drop everything
                    self._spill_cancelled.discard(oid)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                else:
                    self._spilled[oid] = _SpilledObject(oid, path,
                                                        obj.nbytes)
                    self._spilled_bytes_total += obj.nbytes
                self._cv.notify_all()

    def _restore(self, oid: str,
                 timeout: Optional[float] = None) -> Optional[StoredObject]:
        """Two-phase restore mirroring the spill write: claim the
        spill record under the lock, READ THE FILE OUTSIDE IT (a large
        restore must not stall the whole object plane), re-admit under
        the lock. Concurrent getters of the same oid wait on the
        condvar via the _restoring marker. `timeout` bounds how long a
        losing racer waits for the winner's re-admission (0 = don't
        block: the non-blocking-probe contract of get_stored)."""
        with self._cv:
            rec = self._spilled.pop(oid, None)
            if rec is None:
                # Someone else claimed the spill record. If their disk
                # read is still in flight the object is in neither map
                # yet — wait for re-admission instead of reporting a
                # spurious miss to the loser of the race.
                if oid in self._restoring and timeout != 0:
                    self._cv.wait_for(
                        lambda: oid in self._objects
                        or oid not in self._restoring,
                        timeout=timeout)
                return self._objects.get(oid)
            self._restoring.add(oid)
        try:
            with open(rec.path, "rb") as f:
                blob = pickle.load(f)
            os.unlink(rec.path)
        except BaseException:
            with self._cv:
                self._restoring.discard(oid)
                self._spilled[oid] = rec        # put the claim back
                self._cv.notify_all()
            raise
        # Rebuild: buffers go back inline (they re-spill if pressure
        # persists; re-carving shm here would thrash under scans).
        obj = StoredObject(oid, blob["payload"],
                           inline_buffers=list(blob["buffers"]),
                           buffer_order=["i"] * len(blob["buffers"]),
                           is_error=blob["is_error"],
                           contained_ids=list(blob.get("contained", ())))
        with self._cv:
            self._restoring.discard(oid)
            if oid in self._restore_cancelled:   # deleted mid-restore
                self._restore_cancelled.discard(oid)
                self._cv.notify_all()
                return None
            self._objects[oid] = obj
            self._bytes += obj.nbytes
            self._restored_bytes_total += obj.nbytes
            victims = self._pick_victims_locked()
            self._cv.notify_all()
        self._write_spills(victims)
        # Re-admission is a seal: wake registry waiters that parked in
        # the gap before this restore claimed the spill record.
        if self.on_seal is not None:
            self.on_seal(oid)
        return obj

    # ------------------------------------------------- local pinning
    def pin_local(self, object_id: str) -> None:
        """Keep `object_id` resident (not spillable) while a transfer
        serves it — pull sessions hold one for their lifetime so an
        LRU pass can't unlink segments mid-pull."""
        with self._lock:
            self._local_pins[object_id] += 1

    def unpin_local(self, object_id: str) -> None:
        with self._cv:
            n = self._local_pins[object_id] - 1
            if n > 0:
                self._local_pins[object_id] = n
            else:
                self._local_pins.pop(object_id, None)
            self._cv.notify_all()       # backpressure may be waiting

    # ------------------------------------------------------------- get
    def held_objects(self) -> list[tuple[str, int]]:
        """(object_id, nbytes) for every resident or spilled object —
        reported to the head on rejoin so the rehydrated object
        directory learns this node's copies."""
        with self._lock:
            out = [(oid, o.nbytes) for oid, o in self._objects.items()]
            out.extend((oid, s.nbytes) for oid, s in self._spilled.items()
                       if oid not in self._objects)
            return out

    def contains(self, object_id: str) -> bool:
        with self._lock:
            return (object_id in self._objects
                    or object_id in self._spilled
                    or object_id in self._spilling
                    or object_id in self._restoring)

    def get_stored(self, object_id: str,
                   timeout: Optional[float] = None,
                   restore: bool = True) -> Optional[StoredObject]:
        """restore=False is a residency-only probe: spilled objects
        report a miss instead of triggering a synchronous disk read —
        event-driven callers route restores to a worker pool."""
        with self._cv:
            def present():
                return (object_id in self._objects
                        or object_id in self._spilled)
            if timeout != 0:
                self._cv.wait_for(present, timeout=timeout)
            # timeout == 0 is a NON-BLOCKING probe: a mid-flight
            # spill/restore simply reports miss; the caller's blocking
            # path (waiter thread) picks it up once the record lands.
            obj = self._objects.get(object_id)
            if obj is not None:
                self._objects.move_to_end(object_id)   # LRU touch
                self._touched_at[object_id] = time.monotonic()
                return obj
            if object_id not in self._spilled:
                if object_id in self._restoring and timeout != 0:
                    # another thread is reading the spill file: wait for
                    # its re-admission instead of returning a miss
                    self._cv.wait_for(
                        lambda: object_id in self._objects,
                        timeout=timeout)
                    obj = self._objects.get(object_id)
                    if obj is not None:
                        self._touched_at[object_id] = time.monotonic()
                    return obj
                return None
            if not restore:
                return None
        obj = self._restore(object_id, timeout=timeout)
        if obj is not None:
            with self._lock:
                self._touched_at[object_id] = time.monotonic()
        return obj

    def wait_any(self, object_ids: list[str], num_returns: int,
                 timeout: Optional[float]) -> list[str]:
        """Block until >= num_returns of object_ids are local; return ready ids."""
        with self._cv:
            def ready():
                return [o for o in object_ids
                        if o in self._objects or o in self._spilled
                        or o in self._spilling or o in self._restoring]
            self._cv.wait_for(lambda: len(ready()) >= num_returns,
                              timeout=timeout)
            return ready()

    def delete(self, object_id: str) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
            if obj is not None:
                self._bytes -= obj.nbytes
            rec = self._spilled.pop(object_id, None)
            self._touched_at.pop(object_id, None)
            if object_id in self._spilling:
                # mid-flight spill: the writer drops the file + segments
                # when it finishes (see _write_spills)
                self._spill_cancelled.add(object_id)
            if object_id in self._restoring:
                self._restore_cancelled.add(object_id)
        if obj is not None:
            for name in obj.shm_names:
                free_segment(name)
        if rec is not None:
            try:
                os.unlink(rec.path)
            except OSError:
                pass

    def stats(self) -> dict:
        with self._lock:
            out = {
                "num_objects": len(self._objects) + len(self._spilled),
                "bytes": self._bytes,
                "num_spilled": len(self._spilled),
                "spilled_bytes": sum(r.nbytes
                                     for r in self._spilled.values()),
                "spilled_bytes_total": self._spilled_bytes_total,
                "restored_bytes_total": self._restored_bytes_total,
                "capacity_bytes": self.capacity_bytes,
            }
        out.update(SEGMENT_POOL.stats())
        return out

    def shutdown(self) -> None:
        with self._lock:
            ids = list(self._objects) + list(self._spilled)
        for oid in ids:
            self.delete(oid)
        # deletes above may have fed the pool; the session is over, so
        # reap it (the tag-prefixed sweep would catch stragglers too)
        SEGMENT_POOL.clear()
        try:
            os.rmdir(self._spill_dir)
        except OSError:
            pass
