"""Object plane: serialization + shared-memory object store.

Replaces the reference's two-tier object plane (in-process memory store,
reference src/ray/core_worker/store_provider/memory_store/memory_store.h:43,
and the plasma shm arena, reference src/ray/object_manager/plasma/) with:

- ``serialize``/``deserialize`` built on pickle protocol 5 with
  ``buffer_callback``: large contiguous buffers (numpy / jax host arrays)
  are carved out-of-band so cross-process transfer is zero-copy through
  POSIX shared memory, the same property plasma's fd-passing provides
  (reference plasma/fling.cc) without a bespoke arena: the kernel shm
  object *is* the arena and the eviction unit.
- ``LocalStore``: the driver-resident authoritative store. Small payloads
  live inline; each large buffer lives in its own named shm segment,
  unlinked when the distributed refcount hits zero (refcounting lives in
  the controller, reference core_worker/reference_count.cc analogue).

Lifetime design: a segment exists *by name* in the kernel from creation
until ``shm_unlink``; no process needs to hold a handle to keep it alive.
Creators therefore write, then immediately close + unregister from the
resource tracker. Readers map via raw ``mmap`` (not SharedMemory, which
would leak an fd per attach); the mapping is freed automatically when the
last deserialized array view is garbage collected. Unlink-while-mapped is
safe POSIX: existing mappings survive, the name disappears.
"""
from __future__ import annotations

import mmap
import os
import pickle
import threading
import uuid
from dataclasses import dataclass, field
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

import _posixshmem  # CPython's shm syscall wrapper (used by SharedMemory)
import cloudpickle

# Buffers below this many bytes ride inline in the pickled payload; larger
# ones are carved into shm segments. Mirrors the reference's inline-small
# -return threshold semantics (task returns under ~100KiB go to the owner's
# memory store; reference core_worker.h AllocateReturnObject).
INLINE_THRESHOLD = 100 * 1024


def new_object_id() -> str:
    return uuid.uuid4().hex[:20]


@dataclass
class StoredObject:
    """Serialized object: inline payload + optional out-of-band shm buffers."""
    object_id: str
    payload: bytes                      # pickle5 stream (buffers external)
    inline_buffers: list[bytes] = field(default_factory=list)
    shm_names: list[str] = field(default_factory=list)
    shm_sizes: list[int] = field(default_factory=list)
    buffer_order: list[str] = field(default_factory=list)  # "i" inline / "s" shm
    is_error: bool = False              # payload deserializes to an exception

    @property
    def nbytes(self) -> int:
        return (len(self.payload) + sum(len(b) for b in self.inline_buffers)
                + sum(self.shm_sizes))


def _create_segment(name: str, data: memoryview) -> None:
    """Create + fill a named segment, then release all process-local
    resources; the segment persists by name until shm_unlink."""
    shm = shared_memory.SharedMemory(name=name, create=True, size=len(data))
    shm.buf[:len(data)] = data
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # type: ignore[attr-defined]
    except Exception:
        pass
    shm.close()


def _map_segment(name: str, size: int) -> memoryview:
    """Map an existing segment read-write; the fd is closed immediately so
    nothing leaks — the mmap lives as long as views into it do."""
    fd = _posixshmem.shm_open("/" + name, os.O_RDWR, mode=0o600)
    try:
        mm = mmap.mmap(fd, size)
    finally:
        os.close(fd)
    return memoryview(mm)[:size]


def unlink_segment(name: str) -> None:
    try:
        _posixshmem.shm_unlink("/" + name)
    except FileNotFoundError:
        pass
    except OSError:
        pass


def serialize(value: Any, object_id: Optional[str] = None,
              create_shm: bool = True) -> StoredObject:
    object_id = object_id or new_object_id()
    raw_buffers: list[pickle.PickleBuffer] = []
    payload = cloudpickle.dumps(value, protocol=5,
                                buffer_callback=raw_buffers.append)
    inline: list[bytes] = []
    shm_names: list[str] = []
    shm_sizes: list[int] = []
    order: list[str] = []
    for i, pb in enumerate(raw_buffers):
        mv = pb.raw()
        if len(mv) < INLINE_THRESHOLD or not create_shm:
            inline.append(mv.tobytes())
            order.append("i")
        else:
            name = f"rtpu_{object_id}_{i}"
            _create_segment(name, mv)
            shm_names.append(name)
            shm_sizes.append(len(mv))
            order.append("s")
    is_error = isinstance(value, BaseException)
    return StoredObject(object_id, payload, inline, shm_names, shm_sizes,
                        order, is_error)


def deserialize(obj: StoredObject) -> Any:
    """Reconstruct the value. shm-backed buffers become zero-copy views
    whose underlying mappings are freed when the views are collected."""
    buffers: list[Any] = []
    ii = si = 0
    for kind in obj.buffer_order:
        if kind == "i":
            buffers.append(obj.inline_buffers[ii]); ii += 1
        else:
            buffers.append(_map_segment(obj.shm_names[si],
                                        obj.shm_sizes[si])); si += 1
    return pickle.loads(obj.payload, buffers=buffers)


class LocalStore:
    """Driver-resident object store with refcount-driven eviction."""

    def __init__(self):
        self._objects: dict[str, StoredObject] = {}
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def put_stored(self, obj: StoredObject) -> None:
        with self._cv:
            self._objects[obj.object_id] = obj
            self._cv.notify_all()

    def put(self, value: Any, object_id: Optional[str] = None) -> str:
        obj = serialize(value, object_id)
        self.put_stored(obj)
        return obj.object_id

    def contains(self, object_id: str) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_stored(self, object_id: str,
                   timeout: Optional[float] = None) -> Optional[StoredObject]:
        with self._cv:
            if timeout == 0:
                return self._objects.get(object_id)
            ok = self._cv.wait_for(lambda: object_id in self._objects,
                                   timeout=timeout)
            return self._objects.get(object_id) if ok else None

    def wait_any(self, object_ids: list[str], num_returns: int,
                 timeout: Optional[float]) -> list[str]:
        """Block until >= num_returns of object_ids are local; return ready ids."""
        with self._cv:
            def ready():
                return [o for o in object_ids if o in self._objects]
            self._cv.wait_for(lambda: len(ready()) >= num_returns,
                              timeout=timeout)
            return ready()

    def delete(self, object_id: str) -> None:
        with self._lock:
            obj = self._objects.pop(object_id, None)
        if obj is not None:
            for name in obj.shm_names:
                unlink_segment(name)

    def stats(self) -> dict:
        with self._lock:
            return {
                "num_objects": len(self._objects),
                "bytes": sum(o.nbytes for o in self._objects.values()),
            }

    def shutdown(self) -> None:
        with self._lock:
            ids = list(self._objects)
        for oid in ids:
            self.delete(oid)
