"""Tree broadcast: cluster-wide object distribution in O(log N) waves.

``ray_tpu.broadcast(ref)`` distributes one object to every alive node
with the SOURCE serving at most ``bcast_fanout`` transfers (reference
envelope row: 1 GiB object broadcast to 50+ nodes — the workload weight
broadcast for serving and SPMD training leans on; all-pull-from-source
makes the producer the bottleneck at fanout N).

The head coordinates: nodes are arranged in a complete ``fanout``-ary
tree rooted at a holder. Each target gets a BCAST_PLAN naming its
PARENT as the pull source; the plan for a node is dispatched only when
its parent's copy registers in the object directory (the coordinator
listens on directory adds), so every completed puller immediately
serves its subtree while the upper levels are already done. An agent
whose parent fails falls back to its pull manager's multi-source path
(any registered holder), so a mid-tree death degrades to extra load on
the survivors instead of a stuck subtree.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG


def build_tree(order: list[str], fanout: int) -> dict[str, list[str]]:
    """parent node_id -> children node_ids for a complete fanout-ary
    tree over `order` (order[0] is the root/source)."""
    out: dict[str, list[str]] = {}
    for i in range(1, len(order)):
        out.setdefault(order[(i - 1) // fanout], []).append(order[i])
    return out


def tree_depth(n_targets: int, fanout: int) -> int:
    """Depth of the deepest target in a complete fanout-ary tree with
    the source at depth 0 and `n_targets` nodes below it."""
    depth = 0
    i = n_targets              # deepest node sits at index n_targets
    while i > 0:
        i = (i - 1) // fanout
        depth += 1
    return depth


class _Job:
    def __init__(self, object_id: str, nbytes: int, fanout: int,
                 order: list[str]):
        self.object_id = object_id
        self.nbytes = nbytes
        self.fanout = fanout
        self.order = order                  # [source, target, ...]
        self.children = build_tree(order, fanout)
        self.pending: set[str] = set(order[1:])
        self.completed: set[str] = {order[0]}
        self.dispatched: set[str] = set()
        self.failed: set[str] = set()
        self.done = threading.Event()
        self.started = time.monotonic()
        # tracing plane: (trace_id, span_id) of the coordinator's
        # broadcast span; every BCAST_PLAN hop carries it so the
        # cascade's per-node pulls stitch under one timeline root
        self.trace: Optional[tuple] = None

    def snapshot(self) -> dict:
        return {
            "object_id": self.object_id,
            "nbytes": self.nbytes,
            "fanout": self.fanout,
            "source": self.order[0],
            "nodes": len(self.order) - 1,
            "completed": len(self.completed) - 1,
            "failed": sorted(self.failed),
            "depth": tree_depth(len(self.order) - 1, self.fanout),
            "seconds": round(time.monotonic() - self.started, 4),
        }


class BroadcastCoordinator:
    """Head-side: one active job per object id; completions arrive via
    directory add-listener callbacks (OBJECT_ADDED / object_at /
    NODE_TASK_DONE located entries all land there)."""

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._jobs: dict[str, _Job] = {}
        self.trees_built = 0

    # ------------------------------------------------------ directory
    def on_location(self, object_id: str, node_id: str,
                    partial: bool = False) -> None:
        """Directory listener: a node registered a copy — if it is part
        of an active broadcast, unlock its subtree. A PARTIAL
        registration (r12 cut-through: the node landed its first chunk
        and serves landed ranges from the in-flight landing) dispatches
        the children WITHOUT completing the node, so tree depth costs
        per-chunk instead of per-object latency; the full registration
        later completes it (children already dispatched are skipped by
        the job's dispatched set)."""
        with self._lock:
            job = self._jobs.get(object_id)
            if job is None or node_id not in job.pending:
                return
            if not partial:
                job.pending.discard(node_id)
                job.completed.add(node_id)
            to_dispatch = [c for c in job.children.get(node_id, ())
                           if c not in job.dispatched]
            if not job.pending:
                job.done.set()
        for child in to_dispatch:
            self._dispatch(job, child, parent=node_id)

    # ------------------------------------------------------- dispatch
    def _describe(self, node_id: str) -> dict:
        """Source descriptor a child agent can dial."""
        if node_id == self._rt.head_node_id:
            return {"head": True, "node_id": node_id}
        rec = self._rt.cluster.get_node(node_id)
        addr = getattr(rec.scheduler, "advertise_addr",
                       None) if rec else None
        if addr is None:
            return {"head": True, "node_id": node_id}  # degraded: pull head
        return {"host": addr[0], "port": int(addr[1]),
                "node_id": node_id}

    def _dispatch(self, job: _Job, node_id: str, parent: str) -> None:
        with self._lock:
            if node_id in job.dispatched:
                return
            job.dispatched.add(node_id)
        rec = self._rt.cluster.get_node(node_id)
        conn = getattr(rec.scheduler, "conn", None) if rec else None
        ok = False
        if conn is not None and rec.alive:
            try:
                plan = {"type": protocol.BCAST_PLAN,
                        "object_id": job.object_id,
                        "nbytes": job.nbytes,
                        "source": self._describe(parent)}
                if job.trace is not None:
                    plan["_trace"] = job.trace
                conn.send(plan)
                ok = True
            except protocol.ConnectionClosed:
                ok = False
        if not ok:
            self._fail_node(job, node_id)

    def _fail_node(self, job: _Job, node_id: str) -> None:
        """Mark a target failed and re-root its children on the source
        (their pull managers fall back to any holder regardless)."""
        with self._lock:
            if node_id not in job.pending:
                return
            job.pending.discard(node_id)
            job.failed.add(node_id)
            children = [c for c in job.children.get(node_id, ())
                        if c not in job.dispatched]
            if not job.pending:
                job.done.set()
        for child in children:
            self._dispatch(job, child, parent=job.order[0])

    # ------------------------------------------------------------ api
    def broadcast(self, object_id: str, fanout: Optional[int] = None,
                  timeout: Optional[float] = None) -> dict:
        """Distribute `object_id` to every alive agent node; blocks
        until all copies register (or timeout). Returns job stats.
        Concurrent broadcasts of one object join the active job."""
        with _tp.span("bcast", "bcast:" + object_id[:16], root=True):
            return self._broadcast_inner(object_id, fanout, timeout)

    def _broadcast_inner(self, object_id: str,
                         fanout: Optional[int] = None,
                         timeout: Optional[float] = None) -> dict:
        fanout = max(1, int(fanout or _CFG.bcast_fanout))
        timeout = timeout if timeout is not None else _CFG.bcast_timeout_s
        rt = self._rt
        holders = set(rt.controller.locations(object_id))
        head_has = rt.store.contains(object_id)
        if head_has:
            holders.add(rt.head_node_id)
        if not holders:
            # not sealed anywhere yet: wait for it (producer may still
            # be running) via the cluster-wide blocking fetch
            stored = rt._get_stored_anywhere(object_id, timeout)
            if stored is None:
                raise TimeoutError(
                    f"broadcast({object_id}): object not available "
                    f"within {timeout}s")
            holders = set(rt.controller.locations(object_id))
            holders.add(rt.head_node_id)
        # source: prefer the head (it can serve any agent without a
        # peer dial), else any agent holder
        source = (rt.head_node_id if rt.head_node_id in holders
                  else sorted(holders)[0])
        nbytes = rt.controller.directory.nbytes(object_id)
        if not nbytes:
            stored = rt.store.get_stored(object_id, timeout=0,
                                         restore=False)
            if stored is not None:
                nbytes = stored.nbytes
        targets = [n.node_id for n in rt.cluster.alive_nodes()
                   if getattr(n.scheduler, "conn", None) is not None
                   and n.node_id not in holders]
        with self._lock:
            job = self._jobs.get(object_id)
            if job is None:
                if not targets:
                    snap = _Job(object_id, nbytes, fanout,
                                [source]).snapshot()
                    snap["timed_out"] = False   # same shape everywhere
                    return snap
                job = _Job(object_id, nbytes, fanout, [source] + targets)
                job.trace = _tp.wire_ctx()
                self._jobs[object_id] = job
                self.trees_built += 1
                owner = True
            else:
                owner = False
        if owner:
            for child in job.children.get(source, ()):
                self._dispatch(job, child, parent=source)
            # close the registration race: a target whose copy (or
            # first cut-through chunk) landed between the target-list
            # read and the job registration will never fire another
            # directory add event
            for nid in list(job.pending):
                if rt.controller.directory.holds(object_id, nid):
                    self.on_location(object_id, nid)
                elif rt.controller.directory.holds_partial(object_id,
                                                           nid):
                    self.on_location(object_id, nid, partial=True)
        # wait in slices so dead nodes are pruned promptly
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not job.done.is_set():
            left = (None if deadline is None
                    else deadline - time.monotonic())
            if left is not None and left <= 0:
                break
            job.done.wait(timeout=0.25 if left is None
                          else min(0.25, left))
            alive = {n.node_id for n in rt.cluster.alive_nodes()}
            with self._lock:
                lost = [nid for nid in job.pending if nid not in alive]
            for nid in lost:
                self._fail_node(job, nid)
        if owner:
            with self._lock:
                self._jobs.pop(object_id, None)
        snap = job.snapshot()
        snap["timed_out"] = not job.done.is_set()
        return snap

    def stats(self) -> dict:
        with self._lock:
            return {"active_jobs": len(self._jobs),
                    "trees_built": self.trees_built}
