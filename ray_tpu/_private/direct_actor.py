"""Direct actor call plane (r18): peer-to-peer submission + inline replies.

Reference parity: the L0 core worker submits actor tasks worker-to-
worker with the GCS only resolving the actor's location
(src/ray/core_worker/transport/actor_task_submitter.cc + the
sequential actor submit queue). Here the head answers a one-time
``ACTOR_RESOLVE`` with the actor's endpoint — the hosting agent's (or
head's) listener address, the actor's worker id, its restart epoch,
and the node incarnation — the caller caches it and streams
``ACTOR_TASK_DIRECT`` frames over ONE dialed connection, and replies
return inline on the same connection. Steady-state actor calls touch
the head zero times; the head stays the owner of actor lifecycle
through the caller's coalesced ``ACTOR_INFLIGHT_DELTA`` mirror (the
r16 decref-delta discipline), so actor death/restart still produces
``ActorDiedError``/requeue with first-terminal-wins semantics.

Ordering: calls submitted through one handle ride one TCP stream to
the hosting node, which forwards them to the actor's worker in arrival
order — the per-handle submission-order guarantee ``actor.py``
promises holds on the direct path. On any failure (NACK redirect,
endpoint death) the caller flips the actor to STICKY head-routed
fallback: the NACKed calls re-enter the head's queue in submission
order via the mirror, and every later call takes the head path behind
them, so a direct call can never overtake an earlier fallback call.
The driver re-enables direct mode once its inflight/queued books for
the actor are empty (all prior calls reached a terminal state); worker
callers, which cannot observe head-path completion, stay head-routed
for the actor's lifetime after a fallback — sound, and restarts are
rare.

Split of roles in this module:
- ``PendingDirectCalls``: host-side registry (agent and head-as-host)
  of calls forwarded to a worker whose reply the dialed caller is
  still owed. Worker death NACKs every pending call (started=True —
  ambiguous, routed through the head's retry budget).
- ``WorkerDirectCaller``: the caller side for worker/client processes
  (the driver's caller lives in runtime.py where the bookkeeping is
  in-process and free). Holds the endpoint + connection caches, the
  reply-future table, the inline-result cache consumed by get(), and
  the coalesced inflight-delta buffer.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional

from ray_tpu._private import protocol
from ray_tpu._private import tracing_plane as _tp
from ray_tpu._private.config import CONFIG as _CFG

# Negative-resolve cache TTL: an actor that resolved pending/dead/
# non-direct is not re-resolved for this long, so a pending actor's
# caller doesn't pay one resolve round-trip per call while it starts.
_NEG_TTL_S = 0.5


def new_stats() -> dict:
    """One counter dict shape for every party (caller and host), so
    /metrics and state ops render uniformly."""
    return {
        "direct_calls": 0,        # caller: calls sent direct
        "direct_replies": 0,      # caller: inline replies applied
        "inline_bytes": 0,        # caller: bytes landed via replies
        "fallbacks": 0,           # caller: calls sent head-routed
                                  #   while the actor is in fallback
        "redirects": 0,           # caller: NACKs / dead-conn failures
        "resolves": 0,            # caller: ACTOR_RESOLVE round trips
        "stale_replies": 0,       # caller: replies for calls another
                                  #   path already resolved (dropped)
        "served": 0,              # host: direct calls forwarded
        "nacks": 0,               # host: calls NACKed (stale endpoint,
                                  #   fenced node, head-disconnected)
        "served_bytes": 0,        # host: inline reply bytes emitted
    }


class PendingDirectCalls:
    """Host-side table of direct calls awaiting their worker's
    TASK_DONE: task_id -> (caller conn, rid, worker_id).
    The popper owns the reply."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_task: dict[str, tuple] = {}

    def add(self, task_id: str, conn, rid, worker_id: str) -> None:
        with self._lock:
            self._by_task[task_id] = (conn, rid, worker_id)

    def pop(self, task_id: str) -> Optional[tuple]:
        with self._lock:
            return self._by_task.pop(task_id, None)

    def pop_worker(self, worker_id: str) -> list[tuple]:
        """Every pending entry bound to a dead worker, as
        (task_id, conn, rid)."""
        with self._lock:
            hits = [(t, e[0], e[1])
                    for t, e in self._by_task.items()
                    if e[2] == worker_id]
            for t, *_ in hits:
                self._by_task.pop(t, None)
            return hits

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_task)


def dial_cached(cache: dict, lock, addr: tuple,
                poller=None, handler=None,
                on_close=None) -> Optional[protocol.Connection]:
    """Shared endpoint-connection cache (driver and worker callers):
    return the live cached connection for ``addr`` or dial a fresh
    one; a concurrent dial keeps the winner already in the cache and
    closes the loser. None when the endpoint refuses.

    ``handler``/``on_close`` customize the dialed connection for
    planes that receive server-PUSHED frames on it (the serve/llm
    token stream) — the default drops unsolicited frames, which is
    right for the call/reply direct plane."""
    with lock:
        c = cache.get(addr)
        if c is not None and not c.closed:
            return c
    try:
        c = protocol.connect(addr, handler or (lambda conn, m: None),
                             on_close=on_close,
                             name=f"direct-{addr[0]}:{addr[1]}",
                             poller=poller)
    except OSError:
        return None
    with lock:
        existing = cache.get(addr)
        if existing is not None and not existing.closed:
            try:
                c.close()
            except Exception:
                pass
            return existing
        cache[addr] = c
    return c


def nack(conn, rid, reason: str, started: bool) -> None:
    """Answer a direct call with a redirect-to-head NACK. ``started``
    tells the caller whether the task may have begun executing
    (ambiguous — charge the retry budget) or provably never reached
    the worker (safe requeue)."""
    try:
        conn.reply({"rid": rid}, redirect=True, started=bool(started),
                   reason=reason)
    except protocol.ConnectionClosed:
        pass


class WorkerDirectCaller:
    """Caller-side direct plane for worker/client contexts.

    The context owns one instance; ``submit`` returns True when the
    call went direct (the reply future drives completion) and False
    when the caller should take the head-routed path."""

    def __init__(self, ctx):
        self._ctx = ctx                       # WorkerContext/Client
        self._lock = threading.Lock()
        # reply arrival signal: _on_reply/_fail notify under _lock;
        # wait_inline blocks here instead of polling (a sleep-poll
        # cost ~1 ms per sync call — the reply callback runs AFTER
        # the future's waiters wake, so polling always lost the race)
        self._cv = threading.Condition(self._lock)
        self._endpoints: dict[str, dict] = {}     # actor_id -> ep
        self._neg: dict[str, float] = {}          # actor_id -> retry t
        self._conns: dict[tuple, protocol.Connection] = {}
        self._fallback: set[str] = set()          # sticky head-routed
        # task_id -> (future, actor_id); oid -> task_id for get()
        self._pending: dict[str, tuple] = {}
        self._actor_pending: dict[str, int] = {}   # per-actor inflight
        # task ids whose get() stalled out and fell back to the head:
        # resolution authority transferred — a late reply still ships
        # its done delta (a slow call resolves via the head's seal)
        # but must NOT populate the local cache, or a zombie value
        # could shadow the head's first-terminal-wins outcome
        self._head_owned: set[str] = set()
        self._oid_task: dict[str, str] = {}
        self._results: dict[str, Any] = {}        # oid -> StoredObject
        self.stats = new_stats()
        self.last_redirect_reason: Optional[str] = None
        # coalesced ACTOR_INFLIGHT_DELTA buffer (r16 decref-delta
        # discipline): adds flush eagerly-ish so the head's pin lands
        # before the caller's own later decrefs can release an arg
        # ref; dones ride the window (delaying a release is safe).
        # The window is ADAPTIVE (r20): a fixed 25 ms window amortizes
        # a 1k calls/s sync caller to <0.1 head frames/call but bills
        # a sparse caller (an RL env-runner pacing ~60 act()/s against
        # env steps) nearly one frame per call — near-empty frames
        # widen the next window up to the cap, near-full frames snap
        # it back so high-rate callers keep the tight window. Nothing
        # in the delta is latency-critical (args ride a call-lifetime
        # borrow), so only crash-loss scope grows with the window.
        self._delta_lock = threading.Lock()
        self._delta_buf: list = []
        self._delta_window_ms: Optional[float] = None   # None = base
        self._delta_flusher = protocol.FlushLoop(
            self.flush_delta, self._delta_delay_ms,
            "rtpu-direct-delta")

    def _delta_delay_ms(self) -> float:
        base = _CFG.direct_actor_delta_delay_ms
        cur = self._delta_window_ms
        return base if cur is None else max(base, cur)

    # ------------------------------------------------------ gating
    def enabled(self) -> bool:
        return bool(_CFG.direct_actor) and \
            self._ctx.conn.peer_speaks_direct_actor()

    # ---------------------------------------------------- endpoints
    def _endpoint(self, actor_id: str) -> Optional[dict]:
        with self._lock:
            ep = self._endpoints.get(actor_id)
            if ep is not None:
                refresh = ep.get("_refresh_at")
                if (refresh is not None
                        and time.monotonic() > refresh
                        and not self._actor_pending.get(actor_id)):
                    # quiet moment on a provisional (agent-hosted)
                    # endpoint: drop it and re-resolve — the worker's
                    # own socket may be known by now
                    self._endpoints.pop(actor_id, None)
                else:
                    return ep
            if self._neg.get(actor_id, 0) > time.monotonic():
                return None
        try:
            rep = self._ctx.conn.request(
                {"type": protocol.ACTOR_RESOLVE, "actor_id": actor_id},
                timeout=10.0)
        except (protocol.ConnectionClosed, TimeoutError):
            return None
        self.stats["resolves"] += 1
        if not rep.get("direct"):
            with self._lock:
                self._neg[actor_id] = time.monotonic() + _NEG_TTL_S
            return None
        ep = {"host": rep["host"], "port": int(rep["port"]),
              "worker_id": rep["worker_id"],
              "node_id": rep.get("node_id"),
              "epoch": int(rep.get("epoch", 0)),
              "incarnation": rep.get("incarnation")}
        if rep.get("provisional"):
            # agent-hosted because the worker's own port wasn't known
            # yet: re-resolve once the stream quiesces to upgrade to
            # the worker's socket (never mid-stream — two inbound
            # channels to one worker could reorder the handle's calls)
            ep["_refresh_at"] = time.monotonic() + 1.0
        with self._lock:
            self._endpoints[actor_id] = ep
        return ep

    def _dec_actor_pending(self, actor_id: str) -> None:
        """Caller holds self._lock."""
        n = self._actor_pending.get(actor_id, 0) - 1
        if n <= 0:
            self._actor_pending.pop(actor_id, None)
        else:
            self._actor_pending[actor_id] = n

    def _invalidate(self, actor_id: str, sticky: bool = True) -> None:
        with self._lock:
            self._endpoints.pop(actor_id, None)
            if sticky:
                self._fallback.add(actor_id)

    def on_actor_died(self, actor_id: str) -> None:
        """The caller just surfaced an ActorDiedError for this actor:
        drop its cached endpoint AND the negative-resolve memo so a
        restarted incarnation is re-resolved on the very next call
        instead of waiting out a stale-endpoint NACK round-trip (or
        the _NEG_TTL_S backoff from a resolve that raced the restart).
        The sticky fallback flag is cleared only when no calls are in
        flight — with pending books the NACK/fail ordering discipline
        still owns the flag."""
        with self._lock:
            self._endpoints.pop(actor_id, None)
            self._neg.pop(actor_id, None)
            if not self._actor_pending.get(actor_id):
                self._fallback.discard(actor_id)

    def _conn_for(self, ep: dict) -> Optional[protocol.Connection]:
        return dial_cached(self._conns, self._lock,
                           (ep["host"], ep["port"]))

    # ------------------------------------------------------- submit
    def submit(self, actor_id: str, spec) -> bool:
        if not self.enabled():
            return False
        with self._lock:
            if actor_id in self._fallback:
                self.stats["fallbacks"] += 1
                return False
        ep = self._endpoint(actor_id)
        if ep is None:
            return False
        conn = self._conn_for(ep)
        if conn is None:
            self._invalidate(actor_id, sticky=False)
            return False
        # chaos rules match by peer node id: a partition of the
        # hosting node must park this plane's frames too
        if ep.get("node_id"):
            conn.meta.setdefault("chaos_peer", ep["node_id"])
        # arg-ref protection: the caller holds an extra borrow on each
        # pinned arg for the call's lifetime (released on completion),
        # so the mirror-add — whose head-side pin used to be the only
        # guard — can coalesce lazily without opening a delete window.
        # The ADDREF rides the caller's conn AHEAD of any later decref
        # of the same ref (FIFO), exactly the submit-pin discipline of
        # the head-routed path.
        for oid in spec.pinned_refs:
            self._ctx.addref(oid)
        self._park_delta(("add", actor_id, spec))
        msg = {"type": protocol.ACTOR_TASK_DIRECT, "spec": spec,
               "actor_id": actor_id, "worker_id": ep["worker_id"],
               "epoch": ep["epoch"],
               "node_incarnation": ep["incarnation"]}
        if _tp.enabled() and getattr(spec, "trace_id", 0):
            msg["_trace"] = (spec.trace_id,
                             getattr(spec, "parent_span", 0))
        with self._lock:
            self._pending[spec.task_id] = (None, actor_id)
            self._actor_pending[actor_id] = \
                self._actor_pending.get(actor_id, 0) + 1
            for oid in spec.return_ids:
                self._oid_task[oid] = spec.task_id
        try:
            fut = conn.request_async(msg)
        except protocol.ConnectionClosed:
            with self._lock:
                self._pending.pop(spec.task_id, None)
                self._dec_actor_pending(actor_id)
                for oid in spec.return_ids:
                    self._oid_task.pop(oid, None)
            self._invalidate(actor_id, sticky=False)
            # mirror hygiene: retract the add we just parked, release
            # the call-lifetime borrow (the head-routed resubmission
            # the caller falls back to pins through its own path)
            self._park_delta(("done", actor_id, spec.task_id, False,
                              [], True))
            if spec.pinned_refs:
                self._ctx.decref_batch(list(spec.pinned_refs))
            return False
        with self._lock:
            if spec.task_id in self._pending:
                self._pending[spec.task_id] = (fut, actor_id)
        self.stats["direct_calls"] += 1
        fut.add_done_callback(
            lambda f, a=actor_id, s=spec: self._on_reply(a, s, f))
        return True

    # -------------------------------------------------- completion
    def _on_reply(self, actor_id: str, spec, fut) -> None:
        t0 = _tp.now() if _tp.enabled() else 0
        try:
            rep = fut.result(timeout=0)
        except BaseException:
            self._fail(actor_id, spec, started=True, reason="conn_lost")
            return
        if rep.get("redirect"):
            self._fail(actor_id, spec,
                       started=bool(rep.get("started")),
                       reason=rep.get("reason", "redirect"))
            return
        with self._lock:
            if self._pending.pop(spec.task_id, None) is None:
                self.stats["stale_replies"] += 1
                return                  # another path already resolved
            self._dec_actor_pending(actor_id)
            head_owned = spec.task_id in self._head_owned
            self._head_owned.discard(spec.task_id)
            if not head_owned:
                for stored in rep.get("inline", ()):
                    self._results[stored.object_id] = stored
                    self.stats["inline_bytes"] += stored.nbytes
            self._cv.notify_all()
        self.stats["direct_replies"] += 1
        if _tp.enabled() and getattr(spec, "trace_id", 0):
            _tp.record("direct", "reply:" + (spec.name or ""), t0,
                       _tp.now(), spec.trace_id, _tp.new_id(),
                       getattr(spec, "parent_span", 0))
        # the done entry carries the inline results to the head, which
        # seals them as the owner-side copy (exactly where the head-
        # routed path put them) — coalesced, so N calls amortize into
        # one frame and the head pays a store insert, not a route
        self._park_delta(("done", actor_id, spec.task_id,
                          bool(rep.get("error")),
                          list(rep.get("located", ())), False,
                          list(rep.get("inline", ()))))
        if spec.pinned_refs:
            self._ctx.decref_batch(list(spec.pinned_refs))

    def _fail(self, actor_id: str, spec, started: bool,
              reason: str) -> None:
        """A direct call came back NACKed or its connection died:
        sticky-fallback the actor and route the call itself back
        through the head's retry machinery via an EAGER mirror entry
        (the head owns requeue-vs-error: never-started calls requeue
        free, ambiguous ones charge the retry budget). Ordering: the
        fail delta is FLUSHED before the fallback flag publishes — a
        submit that observes the flag and goes head-routed rides the
        same connection BEHIND the delta, so the head order-stamps
        the NACKed call ahead of it."""
        with self._lock:
            if self._pending.pop(spec.task_id, None) is None:
                self.stats["stale_replies"] += 1
                return
            self._dec_actor_pending(actor_id)
            self._head_owned.discard(spec.task_id)
            self._cv.notify_all()
        self.stats["redirects"] += 1
        self.last_redirect_reason = reason      # debug surface
        self._park_delta(
            ("fail", actor_id, spec.task_id, bool(started)))
        self.flush_delta()   # before the fallback flag publishes
        self._invalidate(actor_id, sticky=True)
        if spec.pinned_refs:
            # the head's requeue re-pins through its own machinery;
            # release the call-lifetime borrow
            self._ctx.decref_batch(list(spec.pinned_refs))

    # ------------------------------------------------- get() hooks
    def take_inline(self, oid: str):
        """Inline-reply StoredObject for a return oid, or None. NOT
        popped — a ref may be gotten more than once; the entry dies
        with the ref (release hook) or at actor cleanup."""
        with self._lock:
            return self._results.get(oid)

    def wait_inline(self, oid: str,
                    timeout: Optional[float]) -> Optional[Any]:
        """Wait for oid's direct reply: the StoredObject on success,
        None when the caller should take the normal head-routed GET
        path — no direct call pending, reply resolved without an
        inline result for this oid (located large result, NACK, error
        routed through the head), or the stall budget expired (the
        silent-partition escape hatch: the head errors its mirrored
        in-flight calls, and the fallback get resolves that)."""
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        stall_deadline = time.monotonic() + \
            max(0.1, _CFG.direct_actor_stall_s)
        # _on_reply/_fail pop the pending entry, write the inline
        # results, and notify — all under this lock — so "entry gone +
        # cache miss" conclusively means the head path owns resolution
        with self._cv:
            while True:
                got = self._results.get(oid)
                if got is not None:
                    return got
                tid = self._oid_task.get(oid)
                if tid is None:
                    return None          # never a direct call
                if tid in self._head_owned:
                    return None          # already stalled out once
                if tid not in self._pending:
                    return None          # resolved without inline
                now = time.monotonic()
                if now > stall_deadline:
                    # stall fallback: resolution authority transfers
                    # to the head for THIS call — a late reply still
                    # ships its done delta (slow calls resolve via the
                    # head's seal) but won't populate the local cache
                    self._head_owned.add(tid)
                    return None
                if deadline is not None and now > deadline:
                    return None          # caller deadline: head path
                budget = stall_deadline - now
                if deadline is not None:
                    budget = min(budget, deadline - now)
                self._cv.wait(min(0.2, max(0.001, budget)))

    def release(self, oids) -> None:
        """Ref released (decref flush): drop the cached inline
        results — ownership accounting for inline-returned values."""
        with self._lock:
            for oid in oids:
                self._results.pop(oid, None)
                self._oid_task.pop(oid, None)

    # ------------------------------------------------ mirror delta
    def _park_delta(self, entry: tuple) -> None:
        with self._delta_lock:
            self._delta_buf.append(entry)
            n = len(self._delta_buf)
        if n >= max(1, _CFG.direct_actor_delta_max):
            self.flush_delta()
        else:
            self._delta_flusher.wake()

    def flush_delta(self) -> None:
        with self._delta_lock:
            if not self._delta_buf:
                return
            batch, self._delta_buf = self._delta_buf, []
        # adapt the next collect window to this frame's fill, steering
        # toward half-full frames (delta_max/2 entries): an emptier
        # frame (sparse caller) doubles the window toward the cap, a
        # fuller one (high-rate caller already amortizing) halves it
        # toward the base. Geometric steps both ways — the window
        # tracks rate shifts within a few flushes and a mid-rate
        # caller hovers around the half-full target instead of
        # sawtoothing between cap and base.
        base = _CFG.direct_actor_delta_delay_ms
        cap = max(base, _CFG.direct_actor_delta_delay_max_ms)
        cur = self._delta_window_ms or base
        if len(batch) >= max(1, _CFG.direct_actor_delta_max) // 2:
            self._delta_window_ms = max(base, cur / 2)
        else:
            self._delta_window_ms = min(cap, cur * 2)
        adds, dones = [], []
        for e in batch:
            if e[0] == "add":
                adds.append((e[1], e[2]))
            elif e[0] == "done":
                dones.append({"actor_id": e[1], "task_id": e[2],
                              "error": e[3], "located": e[4],
                              "retract": e[5],
                              "inline": e[6] if len(e) > 6 else []})
            else:                                    # "fail"
                dones.append({"actor_id": e[1], "task_id": e[2],
                              "failed": True, "started": e[3]})
        try:
            self._ctx.conn.send({"type": protocol.ACTOR_INFLIGHT_DELTA,
                                 "adds": adds, "dones": dones,
                                 "caller": getattr(self._ctx,
                                                   "worker_id", None)})
        except protocol.ConnectionClosed:
            pass

    def shutdown(self) -> None:
        self._delta_flusher.stop()
        try:
            self.flush_delta()
        except Exception:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
