"""Wire protocol for the ray_tpu runtime.

Design: a single full-duplex, length-prefixed-frame protocol over TCP
(localhost) or later unix sockets. Either endpoint may send *requests*
(carry a fresh ``rid``) and *replies* (echo the ``rid``). A ``Connection``
owns a reader thread that routes replies to waiting futures and hands
requests to a handler callback, so both sides can issue RPCs concurrently
(a worker blocked in a nested ``get()`` keeps receiving pushed tasks).

This replaces the reference's per-service gRPC stack (reference
src/ray/rpc/: gcs_server/, node_manager/, worker/) with one multiplexed
channel per process pair — appropriate because our control plane is
centralized in the driver process for the single-node runtime, and the
bulk data plane is shared memory, not the socket.

Frame bodies are versioned protobuf Envelopes (`ray_tpu/protos/
wire.proto` via `_private/wire.py`): control data is schema'd and
language-neutral; Python-only payloads ride an explicit `pickled`
bytes leaf. A peer with an incompatible wire MAJOR version is refused
at the first frame, before any pickled leaf is decoded.
"""
from __future__ import annotations

import itertools
import os
import select as _select
import socket
import struct
import threading
import time
from typing import Any, Callable, Optional

from ray_tpu import native
from ray_tpu._private.wire import (BATCH_MIN_MINOR, BATCH_TYPE,
                                   CHANNEL_MIN_MINOR,
                                   DECREF_DELTA_MIN_MINOR,
                                   DELEGATE_MIN_MINOR,
                                   DIRECT_ACTOR_MIN_MINOR,
                                   MANIFEST_MIN_MINOR, METRICS_MIN_MINOR,
                                   RAW_KEY, TRACE_KEY, TRACE_MIN_MINOR,
                                   WIRE_MAJOR, WireVersionError, dumps,
                                   dumps_batch, encode_batch_parts,
                                   encode_frame_parts, loads_ex)

_LEN = struct.Struct("<Q")

# Process-wide frame accounting (this process's connections only):
# physical socket frames vs logical messages, both directions. Read by
# bench_core.py to report control frames per completed task; plain int
# increments under the GIL are accurate enough for benchmarking.
WIRE_STATS = {"tx_frames": 0, "tx_msgs": 0, "rx_frames": 0, "rx_msgs": 0}

# r10 shared-read-loop accounting (this process's Poller, if any):
# plain ints bumped under the GIL on the loop thread — same accuracy
# contract as WIRE_STATS. The metrics plane samples these into gauges
# at scrape time, so the loop itself never touches a metrics lock.
#   passes       service passes that handled >= 1 ready fd
#   frames/bytes complete frames drained through the poller pumps
#   busy_ns      cumulative time spent servicing ready fds
#   max_pass_ns  slowest single servicing pass (the loop-lag ceiling:
#                while one pass runs, every other connection's reads
#                wait this long)
POLLER_STATS = {"passes": 0, "frames": 0, "bytes": 0,
                "busy_ns": 0, "max_pass_ns": 0}

# Message types (flat namespace; direction noted).
REGISTER = "register"            # worker -> driver
TASK = "task"                    # driver -> worker: run a normal task
ACTOR_CREATE = "actor_create"    # driver -> worker: instantiate actor
ACTOR_TASK = "actor_task"        # driver -> worker: run actor method
TASK_DONE = "task_done"          # worker -> driver (reply to TASK/ACTOR_*)
GET_OBJECT = "get_object"        # worker -> driver
PUT_OBJECT = "put_object"        # worker -> driver
WAIT = "wait"                    # worker -> driver
SUBMIT = "submit"                # worker -> driver: nested task submission
SUBMIT_ACTOR = "submit_actor"    # worker -> driver: nested actor creation
SUBMIT_ACTOR_TASK = "submit_actor_task"  # worker -> driver
KV_OP = "kv_op"                  # worker -> driver: internal KV get/put/del
DECREF = "decref"                # worker -> driver: ref-count release
ADDREF = "addref"                # worker -> driver
SHUTDOWN = "shutdown"            # driver -> worker
CANCEL_TASK = "cancel_task"      # driver -> worker: interrupt a running task
UNQUEUE_TASK = "unqueue_task"    # driver -> worker: drop a pipelined task
                                 #   that has not started (reply ok)
PING = "ping"                    # either
REPLY = "reply"                  # either (generic reply)
STATE_OP = "state_op"            # worker -> driver: state/metrics queries
DECREF_BATCH = "decref_batch"    # worker -> driver: N ref-count releases
BATCH = BATCH_TYPE               # either: coalesced sub-frames (MINOR>=1)
TRACE_DUMP = "trace_dump"        # collector -> any: drain the peer's
                                 #   flight recorder (reply: dump/processes
                                 #   + monotonic now for clock alignment)
METRICS_DUMP = "metrics_dump"    # collector -> any: snapshot the peer's
                                 #   metrics registry (r11; agents drain
                                 #   their own workers and reply with the
                                 #   whole node, like TRACE_DUMP)

# ---- multi-host: node agent <-> head (reference raylet <-> GCS,
# gcs_node_manager.h:62 HandleRegisterNode; ray_syncer.h:88 resource
# gossip; object_manager.cc node-to-node transfer) ----
NODE_REGISTER = "node_register"        # agent -> head (reply: node_id)
NODE_HEARTBEAT = "node_heartbeat"      # agent -> head: resource view
NODE_ENQUEUE = "node_enqueue"          # head -> agent: spec to queue
NODE_CANCEL_PENDING = "node_cancel_pending"  # head -> agent (reply found)
NODE_CANCEL_RUNNING = "node_cancel_running"  # head -> agent
NODE_KILL_WORKER = "node_kill_worker"  # head -> agent
NODE_SEND_ACTOR_TASK = "node_send_actor_task"  # head -> agent (reply ok)
NODE_RESERVE_BUNDLE = "node_reserve_bundle"    # head -> agent (reply ok)
NODE_RELEASE_BUNDLE = "node_release_bundle"    # head -> agent
NODE_EVENT = "node_event"              # agent -> head: dispatch/lost/
                                       #   object_at location registers/...
NODE_TASK_DONE = "node_task_done"      # agent -> head: control + results
NODE_DELETE_OBJECT = "node_delete_object"      # head -> agent
NODE_SHUTDOWN = "node_shutdown"        # head -> agent
OBJECT_LOOKUP = "object_lookup"        # agent -> head (reply: stored |
                                       #   location | timeout)
PULL_OBJECT = "pull_object"            # any -> holder (reply: pull meta)
PULL_CHUNK = "pull_chunk"              # any -> holder (reply: data)

# ---- object plane v2 (reference object_manager/object_directory.cc +
# pull_manager.cc): cluster object directory + multi-source pulls +
# tree broadcast ----
LOCATE_OBJECT = "locate_object"        # any -> head (reply: locations,
                                       #   head_has, nbytes) — non-blocking
                                       #   directory read for multi-source
OBJECT_ADDED = "object_added"          # agent -> head: local copy sealed
OBJECT_REMOVED = "object_removed"      # agent -> head: copy gone (holder
                                       #   lost it / stale location)
BCAST_PLAN = "bcast_plan"              # head -> agent: pull object_id from
                                       #   the given parent, then serve
                                       #   your subtree

# ---- delegated bulk-lease scheduling (r10; wire MINOR >= 3,
# negotiated by observation like BatchFrame). The head stops being a
# per-task participant: it grants agents BATCHES of queued tasks under
# one lease and learns completions in coalesced batches; per-task
# task_dispatched events are suppressed for leased tasks. ----
NODE_LEASE_BATCH = "node_lease_batch"  # head -> agent: specs + lease_id
                                       #   + resource budget snapshot
NODE_TASK_DONE_BATCH = "node_task_done_batch"  # agent -> head: N task
                                       #   completions (ctrl + inline/
                                       #   located results each)
NODE_LEASE_REVOKE = "node_lease_revoke"  # head -> agent, fire-and-
                                       #   forget: reclaim queued-not-
                                       #   started tasks (UNQUEUE
                                       #   tombstone machinery for
                                       #   worker FIFOs); the hand-back
                                       #   is the agent's buffered
                                       #   "lease_reclaimed" NODE_EVENT,
                                       #   never a reply — a dropped
                                       #   reply must not strand work
NODE_FIND_TASK = "node_find_task"      # head -> agent (reply: state
                                       #   pending|running|None +
                                       #   worker_id) — cancel path's
                                       #   substitute for the
                                       #   suppressed dispatch events
NODE_HB_RESYNC = "node_hb_resync"      # head -> agent: heartbeat seq
                                       #   gap observed; send a full
                                       #   snapshot next beat (N10
                                       #   delta-sync)
NODE_DECREF_DELTA = "node_decref_delta"  # agent -> head (r16; wire
                                       #   MINOR >= 7): coalesced
                                       #   per-object refcount
                                       #   releases {oid: n} + a
                                       #   per-node seq the head
                                       #   watermarks so rejoin
                                       #   replays dedup (the r15
                                       #   done-batch discipline
                                       #   extended to decrefs)
# ---- direct actor call plane (r18; wire MINOR >= 8, negotiated by
# observation like BatchFrame). The head stops being a per-call party:
# a caller resolves the actor's endpoint ONCE, dials the hosting
# node's listener, streams calls over that one connection (per-handle
# submission order rides the stream), and replies return inline on the
# same connection. The head stays the owner of actor lifecycle via the
# caller's coalesced inflight mirror. ----
ACTOR_RESOLVE = "actor_resolve"        # caller -> head (reply: endpoint
                                       #   host/port + worker_id +
                                       #   restart epoch + node
                                       #   incarnation, or direct=False
                                       #   / state=dead|pending)
ACTOR_TASK_DIRECT = "actor_task_direct"  # caller -> hosting agent/head
                                       #   listener (reply: inline
                                       #   results / located hints, or
                                       #   redirect=True NACK with
                                       #   started flag — stale
                                       #   endpoint, fenced node,
                                       #   head-disconnected host)
ACTOR_INFLIGHT_DELTA = "actor_inflight_delta"  # remote caller -> head:
                                       #   coalesced mirror of direct
                                       #   in-flight calls (adds carry
                                       #   the spec so death/restart
                                       #   still produces
                                       #   ActorDiedError/requeue;
                                       #   dones carry located results
                                       #   + containment and release
                                       #   pins; fail/requeue entries
                                       #   route NACKed calls back
                                       #   through the head's retry
                                       #   machinery)
NODE_FENCED = "node_fenced"            # head -> agent (r17): a state-
                                       #   bearing frame arrived from a
                                       #   STALE node incarnation (the
                                       #   node was declared dead while
                                       #   still alive — partition/
                                       #   stall zombie). The frame was
                                       #   dropped; the agent must kill
                                       #   its workers, clear its
                                       #   scheduler/lease ledgers, and
                                       #   re-register fresh.


class ConnectionClosed(Exception):
    pass


class FrameTooLarge(ConnectionClosed):
    """A frame's length prefix exceeds wire_max_frame_bytes: corrupt
    (or hostile) stream. The connection dies before the reader
    attempts a multi-GB allocation; existing ConnectionClosed handling
    covers recovery."""


# ---- protocol-level network fault injection (r17) ----
# One process-wide ChaosNet, constructed lazily ONLY when
# RAY_TPU_CHAOS=1 — with chaos off the module global stays None and
# the hot-path hooks cost a single global load + None check, with
# byte-identical wire behavior. Both engines pass through the hook
# points: every decoded inbound frame funnels through
# Connection._handle_frame and every outbound write through
# Connection._emit_locked, regardless of native/python pump.
_CHAOS_NET: Optional["ChaosNet"] = None


def chaos_net() -> Optional["ChaosNet"]:
    """The process chaos controller, created on first call when
    RAY_TPU_CHAOS=1 (None otherwise). Once created it persists for
    the process; tests clear its rules rather than destroy it."""
    global _CHAOS_NET
    if _CHAOS_NET is None:
        from ray_tpu._private.config import CONFIG
        if not CONFIG.chaos:
            return None
        _CHAOS_NET = ChaosNet(CONFIG.chaos_seed)
    return _CHAOS_NET


class ChaosNet:
    """Deterministic protocol-level fault injection between this
    process and named peers (tests/chaos.py drives it).

    Rules are keyed by peer id — matched against a connection's
    ``meta["node_id"]`` (set at NODE_REGISTER), ``meta["chaos_peer"]``
    (explicit test tag), its ``name``, or the wildcard ``"*"`` — and
    carry a mode:

    - ``partition``: TCP-faithful link partition. Frames are PARKED
      (not lost — a partition makes TCP traffic late, not gone:
      retransmission delivers it after heal), inbound on a relay
      queue, outbound in a per-connection buffer flushed FIFO-ahead
      of the first post-heal write. ``Connection.close()`` on a
      matching connection is DEFERRED: a partitioned link delivers
      no FIN either, so the head declaring the node dead must not
      tear the stream down — after heal the zombie's frames arrive
      on the SAME connection under a stale incarnation, which is
      exactly the split-brain the fencing layer exists to stop. A
      blip shorter than the death timeout instead delivers
      everything late and loses nothing.
    - ``blackhole``: every matching frame vanishes permanently (a
      lossy/asymmetric link, stronger than any real partition).
    - ``drop``: each frame dropped with probability ``p`` from the
      seeded RNG (RAY_TPU_CHAOS_SEED — failing runs replay).
    - ``delay``: inbound frames relay ``delay_s`` late (per-arrival
      FIFO); outbound writes sleep in the emitter (a slow link with
      real backpressure).
    """

    _PARK_CAP = 100_000            # frames parked per direction/conn

    def __init__(self, seed: int = 0):
        import random as _random
        self._rnd = _random.Random(seed)
        self._lock = threading.Lock()
        self._rules: dict[str, dict] = {}
        self.active = False          # fast-path gate: False == no rules
        self.stats = {"dropped_in": 0, "dropped_out": 0, "delayed": 0,
                      "parked_in": 0, "parked_out": 0,
                      "deferred_closes": 0}
        # delay-mode relay: (release_t, conn, frame) in arrival order
        self._delayq: list = []
        # partition-mode parking: id(conn) -> (conn, [frames])
        self._parked_in: dict[int, tuple] = {}
        self._parked_out: dict[int, tuple] = {}
        self._cv = threading.Condition(self._lock)
        self._relay_thread: Optional[threading.Thread] = None
        self._deferred_close: list = []

    # ---- rule management (tests) ----
    def set_rule(self, peer: str, mode: str, direction: str = "both",
                 p: float = 1.0, delay_s: float = 0.0) -> None:
        assert mode in ("partition", "blackhole", "drop", "delay"), mode
        assert direction in ("in", "out", "both"), direction
        with self._lock:
            self._rules[peer] = {"mode": mode, "dir": direction,
                                 "p": float(p), "delay_s": float(delay_s)}
            self.active = True

    def clear(self, peer: Optional[str] = None) -> None:
        """Heal: drop one rule (or all). Parked partition traffic
        drains — the relay thread replays inbound frames FIFO and
        outbound buffers flush ahead of the next write (nudged here so
        an idle direction still delivers). Deferred closes are simply
        forgotten: the link is healthy again and the connection keeps
        serving; if its owner really wanted it gone, the peer's own
        close (or fencing) finishes the job."""
        with self._lock:
            if peer is None:
                self._rules.clear()
            else:
                self._rules.pop(peer, None)
            self.active = bool(self._rules)
            if not self.active:
                self._deferred_close.clear()
            self._ensure_relay_locked()
            self._cv.notify_all()
            flush = [conn for _cid, (conn, frames)
                     in self._parked_out.items() if frames]
        for conn in flush:
            threading.Thread(target=conn._chaos_flush,
                             name="ray-tpu-chaos-flush",
                             daemon=True).start()

    def _rule_for(self, conn: "Connection") -> Optional[dict]:
        rules = self._rules
        meta = conn.meta
        for key in (meta.get("node_id"), meta.get("chaos_peer"),
                    conn.name, "*"):
            if key is not None:
                r = rules.get(key)
                if r is not None:
                    return r
        return None

    def _parks(self, conn: "Connection", direction: str) -> bool:
        rule = self._rule_for(conn)
        return (rule is not None and rule["mode"] == "partition"
                and rule["dir"] in (direction, "both"))

    def _ensure_relay_locked(self) -> None:
        if self._relay_thread is None:
            self._relay_thread = threading.Thread(
                target=self._relay_loop, name="ray-tpu-chaos-relay",
                daemon=True)
            self._relay_thread.start()

    # ---- inbound hook ----
    def on_frame_in(self, conn: "Connection", data: bytes) -> bool:
        """True = the frame was consumed (parked/dropped/delayed);
        False = deliver normally. Loss rules (blackhole/drop) are
        evaluated BEFORE the heal-drain FIFO park: a rule installed
        while a previous partition's backlog is still draining must
        discard fresh frames, not smuggle them through the queue."""
        rule = self._rule_for(conn)
        applies = rule is not None and rule["dir"] in ("in", "both")
        mode = rule["mode"] if applies else None
        with self._lock:
            entry = self._parked_in.get(id(conn))
            if mode == "partition":
                if entry is None:
                    entry = self._parked_in[id(conn)] = (conn, [])
                if len(entry[1]) < self._PARK_CAP:
                    entry[1].append(data)
                    self.stats["parked_in"] += 1
                else:
                    self.stats["dropped_in"] += 1
                self._ensure_relay_locked()
                return True
            if mode == "blackhole" or (
                    mode == "drop"
                    and self._rnd.random() < rule["p"]):
                self.stats["dropped_in"] += 1
                return True
            if entry is not None:
                # heal flush still draining: keep FIFO — this frame
                # queues behind the parked backlog. The entry persists
                # (possibly empty) until the relay thread observes it
                # drained AFTER its last delivery completed, so a
                # fresh frame can never overtake an in-flight parked
                # one (seq-watermarked deltas would drop the late
                # frame as a replay otherwise).
                entry[1].append(data)
                self._ensure_relay_locked()
                self._cv.notify_all()
                return True
            if mode == "delay":
                self._delayq.append(
                    (time.monotonic() + rule["delay_s"], conn, data))
                self.stats["delayed"] += 1
                self._ensure_relay_locked()
                self._cv.notify_all()
                return True
        return False

    # ---- outbound hook (caller holds conn._send_lock) ----
    def filter_out(self, conn: "Connection", frames: list) -> list:
        with self._lock:
            entry = self._parked_out.get(id(conn))
            parks = self._parks(conn, "out")
            if parks:
                if entry is None:
                    entry = self._parked_out[id(conn)] = (conn, [])
                room = self._PARK_CAP - len(entry[1])
                entry[1].extend(frames[:room])
                self.stats["parked_out"] += min(len(frames), room)
                self.stats["dropped_out"] += max(0,
                                                 len(frames) - room)
                return []
            prefix = []
            if entry is not None:
                # healed: parked frames flush FIRST (the caller holds
                # the send lock, so FIFO with this write is exact)
                prefix = entry[1][:]
                del self._parked_out[id(conn)]
        rule = self._rule_for(conn)
        if rule is None or rule["dir"] == "in":
            return prefix + frames
        mode = rule["mode"]
        if mode == "blackhole":
            self.stats["dropped_out"] += len(frames)
            return prefix
        if mode == "drop":
            kept = []
            with self._lock:
                for f in frames:
                    if self._rnd.random() < rule["p"]:
                        self.stats["dropped_out"] += 1
                    else:
                        kept.append(f)
            return prefix + kept
        if mode == "delay":
            time.sleep(rule["delay_s"])  # slow link: real backpressure
        return prefix + frames

    def has_parked_out(self, conn: "Connection") -> bool:
        entry = self._parked_out.get(id(conn))
        return entry is not None and bool(entry[1])

    def defer_close(self, conn: "Connection") -> bool:
        """True when `conn` sits behind an active both-direction
        partition/blackhole: the close is swallowed (recorded) — a
        partitioned link delivers no FIN, so the stream must survive
        for the post-heal fencing exchange."""
        rule = self._rule_for(conn)
        if rule is None or rule["mode"] not in ("partition",
                                                "blackhole") \
                or rule["dir"] != "both":
            return False
        with self._lock:
            self._deferred_close.append(conn)
        self.stats["deferred_closes"] += 1
        return True

    # ---- relay thread: delayed frames + healed partition backlogs ----
    def _relay_loop(self) -> None:
        while True:
            item = None
            with self._lock:
                # healed partitions first: replay parked inbound FIFO
                for cid, (conn, frames) in list(self._parked_in.items()):
                    if self._parks(conn, "in"):
                        continue             # still partitioned
                    if frames:
                        item = (conn, frames.pop(0))
                        break
                    del self._parked_in[cid]
                if item is None and self._delayq:
                    release_t, conn, data = self._delayq[0]
                    wait = release_t - time.monotonic()
                    if wait <= 0:
                        self._delayq.pop(0)
                        item = (conn, data)
                    else:
                        self._cv.wait(min(wait, 0.2))
                        continue
                if item is None:
                    self._cv.wait(0.2)
                    continue
            conn, data = item
            try:
                conn._handle_frame(data, _chaos_checked=True)
            except Exception:
                pass                     # chaos must not kill the relay


def _auth_token() -> Optional[bytes]:
    """Shared listener secret (RAY_TPU_AUTH_TOKEN). When set, every
    accepted connection must present it in a RAW first frame, verified
    with a constant-time compare BEFORE any frame is unpickled — the
    wire is pickle, so an unauthenticated peer would otherwise get
    arbitrary code execution (reference scopes this via gRPC + tokened
    client/job servers, python/ray/util/client/server/)."""
    from ray_tpu._private.config import CONFIG
    tok = CONFIG.auth_token
    return tok.encode() if tok else None


class Connection:
    """Full-duplex framed-message channel with request/reply correlation."""

    def __init__(self, sock: socket.socket,
                 handler: Callable[["Connection", dict], None],
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 name: str = "", server: bool = False,
                 poller: Optional["Poller"] = None):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Bound sends only (recv stays blocking: connections idle for
        # minutes legitimately): waiter-registry replies run inline on
        # sealing threads, so a wedged peer (full TCP buffer) must
        # surface as a ConnectionClosed after this budget instead of
        # hanging the sender forever — peer-death recovery then runs.
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", 30, 0))
        except OSError:
            pass
        self._handler = handler
        self._on_close = on_close
        self.name = name
        self._send_lock = threading.Lock()
        self._rid_counter = itertools.count(1)
        self._pending: dict[int, _Future] = {}
        self._pending_lock = threading.Lock()
        self._closed = threading.Event()
        self._server = server
        self.meta: dict = {}  # endpoint-attached metadata (worker id, etc.)
        # Wire version observed on the peer's frames (0 = nothing seen
        # yet). Batch emission is gated on it: until the peer proves it
        # speaks MINOR >= BATCH_MIN_MINOR, coalesced flushes go out as
        # individual frames in one sendall (compatible with any peer).
        self.peer_wire_version = 0
        # Opt-in coalescing queue (enable_coalescing): fire-and-forget
        # frames park here briefly and flush as one write.
        self._lazy: list[dict] = []
        self._lazy_lock = threading.Lock()
        self._lazy_wake = threading.Event()
        self._lazy_thread: Optional[threading.Thread] = None
        # r10 epoll loop: when a process-level Poller is attached, the
        # read side is driven by its shared event loop instead of a
        # dedicated reader thread. Pump state (native nb-reader or the
        # Python reassembly buffer over a dup'd socket) is created at
        # registration time by the poller.
        self._poller = poller
        self._nb_reader = None          # native.FrameReader (poller)
        self._pump_sock: Optional[socket.socket] = None   # py fallback
        self._pump_buf: Optional[bytearray] = None
        self._pump_eof = False
        self._finished = False
        self._finish_lock = threading.Lock()
        self._reader = threading.Thread(
            target=self._read_loop, name=f"ray-tpu-conn-{name}", daemon=True)

    def start(self) -> None:
        if self._poller is not None and self._poller.alive:
            if self._server and _auth_token() is not None:
                # auth handshake keeps its blocking semantics (size
                # guard + 10s slowloris deadline, verified before ANY
                # unpickling) on a short-lived thread; the connection
                # joins the shared loop once authenticated
                threading.Thread(
                    target=self._auth_then_register,
                    name=f"ray-tpu-auth-{self.name}",
                    daemon=True).start()
            else:
                self._poller.register(self)
            return
        self._poller = None             # poller gone: thread fallback
        self._reader.start()

    def _auth_then_register(self) -> None:
        if not self._check_auth():
            self._finish_read()         # closed: error futures etc.
            return
        poller = self._poller
        if poller is not None and poller.alive:
            poller.register(self)
        else:
            self._poller = None
            self._reader.start()

    def send_auth(self) -> None:
        """Client side: present the shared secret as the raw first
        frame (no-op when auth is disabled)."""
        token = _auth_token()
        if token is None:
            return
        with self._send_lock:
            try:
                self._sock.sendall(_LEN.pack(len(token)) + token)
            except OSError as e:
                self.close()
                raise ConnectionClosed(str(e)) from e

    def _check_auth(self) -> bool:
        """Server side (reader thread): verify the raw first frame
        before ANY unpickling. Closes and returns False on mismatch."""
        token = _auth_token()
        if token is None:
            return True
        try:
            # hard deadline: a peer that connects and sends nothing
            # must not pin this thread + fd forever (slowloris)
            self._sock.settimeout(10.0)
            header = self._read_exact(_LEN.size)
            (length,) = _LEN.unpack(header)
            if length > 4096:           # token frames are tiny
                raise ConnectionClosed("oversized auth frame")
            presented = self._read_exact(length)
            self._sock.settimeout(None)
        except (ConnectionClosed, OSError):
            self.close()        # malformed/short/slow: drop the socket
            return False
        import hmac
        if not hmac.compare_digest(presented, token):
            import sys as _sys
            _sys.stderr.write(
                f"ray_tpu: rejected unauthenticated connection "
                f"({self.name})\n")
            self.close()
            return False
        return True

    # ---- sending ----
    def send(self, msg: dict) -> None:
        """Immediate send. If a coalescing queue is pending, its frames
        are flushed FIRST in the same write — per-connection FIFO order
        is preserved between lazy and eager sends (the refcount
        protocol depends on it: an ADDREF parked in the queue must
        never be overtaken by the TASK_DONE that releases the pin).
        The lazy-queue drain and the socket write happen under one
        lock (_send_lock): draining outside it would let this eager
        frame overtake frames the flusher thread has already swapped
        out of the queue but not yet written."""
        with self._send_lock:
            frames = self._drain_lazy()
            frames.append(msg)
            self._emit_locked(frames)

    def send_lazy(self, msg: dict) -> None:
        """Queue a fire-and-forget frame on the coalescing queue: it
        flushes with its neighbors as one write after ~wire_batch
        thresholds (count / delay), or earlier if an eager send/reply
        follows. Falls back to send() when coalescing is off."""
        from ray_tpu._private.config import CONFIG
        if self._lazy_thread is None or not CONFIG.wire_batch:
            self.send(msg)
            return
        with self._lazy_lock:
            self._lazy.append(msg)
            n = len(self._lazy)
        if n >= CONFIG.wire_batch_max_frames:
            self.flush()
        else:
            self._lazy_wake.set()

    def flush(self) -> None:
        if not self._lazy:
            return
        with self._send_lock:
            frames = self._drain_lazy()
            if frames:
                self._emit_locked(frames)

    def _drain_lazy(self) -> list[dict]:
        """Swap the coalescing queue out. Callers hold _send_lock so
        the drained frames cannot be overtaken by a concurrent eager
        send before they reach the socket (lock order: _send_lock ->
        _lazy_lock; send_lazy takes only _lazy_lock)."""
        if not self._lazy:
            return []
        with self._lazy_lock:
            frames, self._lazy = self._lazy, []
        return frames

    def enable_coalescing(self) -> None:
        """Opt this connection's send_lazy() into micro-batched
        flushing (hot emitters: workers, the dispatch path). Without
        this, send_lazy() behaves exactly like send()."""
        if self._lazy_thread is not None:
            return
        self._lazy_thread = threading.Thread(
            target=self._lazy_flush_loop,
            name=f"ray-tpu-conn-flush-{self.name}", daemon=True)
        self._lazy_thread.start()

    def _lazy_flush_loop(self) -> None:
        from ray_tpu._private.config import CONFIG
        delay = max(0.0, CONFIG.wire_batch_delay_ms / 1000.0)
        while not self._closed.is_set():
            self._lazy_wake.wait()
            if self._closed.is_set():
                return
            if delay:
                # Collect-then-flush: the first frame of a burst opens
                # a `delay`-wide window and every frame emitted inside
                # it rides the same write. A lazy frame therefore waits
                # at most ~delay; anything latency-critical uses the
                # eager send() path, which also drains this queue
                # first, so the window never reorders or starves it.
                time.sleep(delay)
            self._lazy_wake.clear()
            try:
                self.flush()
            except ConnectionClosed:
                return

    def _peer_speaks_batch(self) -> bool:
        v = self.peer_wire_version
        return v // 100 == WIRE_MAJOR and v % 100 >= BATCH_MIN_MINOR

    def peer_speaks_delegate(self) -> bool:
        """Whether the peer demonstrated the delegated-scheduling wire
        (MINOR >= 3). Unknown (0) counts as NO: lease/done-batch ops
        would be silently dropped by an old peer's handler, so the
        sender stays on the per-task protocol until the peer proves
        itself (registration traffic always arrives first in
        practice)."""
        v = self.peer_wire_version
        return v // 100 == WIRE_MAJOR and v % 100 >= DELEGATE_MIN_MINOR

    def peer_speaks_metrics(self) -> bool:
        """Whether the peer answers METRICS_DUMP (MINOR >= 4). Unknown
        (0) counts as NO — an old peer's handler drops the unknown
        type without replying and would burn the collector's shared
        fan-out deadline (same rule as peer_speaks_delegate)."""
        v = self.peer_wire_version
        return v // 100 == WIRE_MAJOR and v % 100 >= METRICS_MIN_MINOR

    def peer_speaks_manifest(self) -> bool:
        """Whether the peer understands the r12 manifest object plane
        (MINOR >= 5). The transfer protocol itself negotiates per
        message (reply-shape, see object_transfer) — this gate exists
        for partial-holder OBJECT_ADDED reports, which an old head
        would misread as full locations. Unknown (0) counts as NO."""
        v = self.peer_wire_version
        return v // 100 == WIRE_MAJOR and v % 100 >= MANIFEST_MIN_MINOR

    def peer_speaks_channel(self) -> bool:
        """Whether the peer's wire-channel endpoint lands Envelope
        `raw` CH_DATA payloads (MINOR >= 6). Unknown (0) counts as NO:
        an older endpoint would decode the frame but miss the raw
        field's tensor, so the writer ships the pickled-body fallback
        until the peer's attach frame demonstrates the MINOR (r13
        wire-channel transport, experimental/wire_channel.py)."""
        v = self.peer_wire_version
        return v // 100 == WIRE_MAJOR and v % 100 >= CHANNEL_MIN_MINOR

    def peer_speaks_decref_delta(self) -> bool:
        """Whether the peer applies NODE_DECREF_DELTA frames
        (MINOR >= 7). Unknown (0) counts as NO: an old head would
        silently drop the unknown type and every release in it would
        leak for the session, so agents forward the workers' own
        DECREF_BATCH frames until the head proves itself."""
        v = self.peer_wire_version
        return (v // 100 == WIRE_MAJOR
                and v % 100 >= DECREF_DELTA_MIN_MINOR)

    def peer_speaks_direct_actor(self) -> bool:
        """Whether the peer speaks the r18 direct actor call plane
        (MINOR >= 8): answers ACTOR_RESOLVE, hosts ACTOR_TASK_DIRECT,
        applies ACTOR_INFLIGHT_DELTA. Unknown (0) counts as NO — an
        old peer drops the unknown types without replying and the
        caller's future would burn its stall budget."""
        v = self.peer_wire_version
        return (v // 100 == WIRE_MAJOR
                and v % 100 >= DIRECT_ACTOR_MIN_MINOR)

    def _peer_speaks_trace(self) -> bool:
        """Whether trace context may ride this connection's envelopes.
        Unknown (0: nothing received yet) counts as yes — trace fields
        are SKIPPABLE unknown fields to any proto3 peer, so the worst
        case is a few wasted bytes on the first frames; once an older
        MINOR is observed, the sender stops spending them."""
        v = self.peer_wire_version
        return v == 0 or (v // 100 == WIRE_MAJOR
                          and v % 100 >= TRACE_MIN_MINOR)

    def _emit_locked(self, frames: list[dict]) -> None:
        """Encode + write a group of frames as ONE socket write: a
        single BatchFrame envelope when the peer negotiated batch
        support, else the individual frames concatenated (one syscall
        either way; the latter is valid toward ANY same-major peer).
        With the native engine the write is one scatter-gather
        sendmsg(2) over (length-prefix, header, payload) buffers — GIL
        released, and a Python-plane frame's pickled body goes from
        the pickler to the kernel with zero copies; the fallback joins
        and sendall()s. Caller holds _send_lock."""
        ch = _CHAOS_NET
        if ch is not None and (ch.active or ch.has_parked_out(self)):
            frames = ch.filter_out(self, frames)
            if not frames:
                return               # swallowed/parked: sender unaware
        if not self._peer_speaks_trace():
            # old-wire peer: strip trace context rather than spend
            # bytes it will skip (copies, not mutation — callers may
            # reuse their message dicts)
            frames = [({k: v for k, v in m.items() if k != TRACE_KEY}
                       if TRACE_KEY in m else m) for m in frames]
        eng_on = native.frame_engine_enabled()
        if len(frames) > 1 and self._peer_speaks_batch():
            parts = (encode_batch_parts(frames) if eng_on
                     else [dumps_batch(frames)])
            bufs = [_LEN.pack(sum(map(len, parts))), *parts]
            WIRE_STATS["tx_frames"] += 1
        else:
            bufs = []
            for msg in frames:
                parts = (encode_frame_parts(msg) if eng_on
                         else [dumps(msg)])
                bufs.append(_LEN.pack(sum(map(len, parts))))
                bufs.extend(parts)
            WIRE_STATS["tx_frames"] += len(frames)
        WIRE_STATS["tx_msgs"] += len(frames)
        total = sum(map(len, bufs))
        try:
            # Scatter-gather pays for its per-buffer setup once the
            # emit is a real burst or carries a big payload; a lone
            # small frame is cheaper joined. sendmsg(2) — not a raw-fd
            # C writev — so the fd stays owned by the socket object: a
            # concurrent close() surfaces as EBADF instead of racing
            # fd reuse and writing this frame into an unrelated
            # connection (the reader pins its fd with a dup for the
            # same reason).
            if eng_on and (len(bufs) > 4 or total >= 1 << 16):
                self._sendmsg_all(bufs, total)
            else:
                self._sock.sendall(b"".join(bufs))
        except OSError as e:
            # A failed write may have put a PARTIAL frame on the wire
            # (e.g. the SO_SNDTIMEO budget expired mid-write); the
            # stream is desynced, so the connection must die — a
            # later send would be parsed as garbage by the peer.
            self.close()
            raise ConnectionClosed(str(e)) from e

    def _sendmsg_all(self, bufs: list, total: int) -> None:
        """Write every buffer as few scatter-gather sendmsg(2)
        syscalls as possible (GIL released per call): chunked at 1024
        buffers (IOV_MAX), partial sends resumed with memoryview
        slices — no byte is ever copied into a joined payload. Raises
        OSError like sendall (EAGAIN = SO_SNDTIMEO expired: stream
        desynced, caller kills the connection)."""
        sent_total = 0
        pos = 0
        while sent_total < total:
            chunk = bufs[pos:pos + 1024]
            want = sum(map(len, chunk))
            sent = self._sock.sendmsg(chunk)
            sent_total += sent
            if sent == want:
                pos += len(chunk)
                continue
            # partial send (kernel buffer full): drop fully-written
            # buffers, slice the straddled one, retry from there
            bufs = bufs[pos:]
            pos = 0
            while sent >= len(bufs[0]):
                sent -= len(bufs[0])
                bufs.pop(0)
            if sent:
                bufs[0] = memoryview(bufs[0])[sent:]

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        """Send a request and block for the matching reply."""
        fut = self.request_async(msg)
        return fut.result(timeout)

    def request_async(self, msg: dict) -> "_Future":
        rid = next(self._rid_counter)
        msg["rid"] = rid
        fut = _Future()
        with self._pending_lock:
            self._pending[rid] = fut
        try:
            self.send(msg)
        except ConnectionClosed:
            with self._pending_lock:
                self._pending.pop(rid, None)
            raise
        return fut

    def reply(self, request_msg: dict, **fields) -> None:
        self.send({"type": REPLY, "rid": request_msg["rid"], **fields})

    # ---- receiving ----
    def _dispatch(self, msg: dict) -> None:
        if msg.get("type") == REPLY:
            with self._pending_lock:
                fut = self._pending.pop(msg["rid"], None)
            if fut is not None:
                fut.set(msg)
        else:
            self._handler(self, msg)

    def _read_exact(self, n: int) -> bytes:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(min(remaining, 1 << 20))
            if not chunk:
                raise ConnectionClosed("peer closed")
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def _handle_frame(self, data: bytes,
                      _chaos_checked: bool = False) -> None:
        """Decode one framed body and dispatch its message(s)."""
        ch = _CHAOS_NET
        if ch is not None and not _chaos_checked and (
                ch.active or ch._parked_in):
            if ch.on_frame_in(self, data):
                return               # parked / dropped / delayed
        msg, version = loads_ex(data)
        self.peer_wire_version = version
        WIRE_STATS["rx_frames"] += 1
        if msg.get("type") == BATCH:
            for sub in msg["frames"]:
                WIRE_STATS["rx_msgs"] += 1
                self._dispatch(sub)
        else:
            WIRE_STATS["rx_msgs"] += 1
            self._dispatch(msg)

    def _native_read_loop(self) -> None:
        """Native pump: blocking read(2) + length-prefix reassembly
        run in C with the GIL RELEASED — the Python loop below holds
        the GIL for every chunk recv and header parse, actively
        starving the handler/sender threads on few-core hosts. One
        pump call returns every complete frame it buffered."""
        from ray_tpu._private.config import CONFIG
        reader = native.FrameReader(self._sock.fileno(),
                                    CONFIG.wire_max_frame_bytes)
        try:
            while True:
                try:
                    frames = reader.pump()
                except native.PumpClosed:
                    raise ConnectionClosed("peer closed") from None
                except native.PumpOversized as e:
                    raise FrameTooLarge(str(e)) from None
                for frame in frames:
                    self._handle_frame(frame)
        finally:
            reader.close()

    def _py_read_loop(self) -> None:
        """Pure-Python fallback: one reassembly bytearray per
        connection (amortized append, no per-chunk bytes concat), with
        the same max-frame-size guard as the native pump."""
        from ray_tpu._private.config import CONFIG
        max_frame = CONFIG.wire_max_frame_bytes
        buf = bytearray()
        while True:
            while len(buf) < _LEN.size:
                chunk = self._sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionClosed("peer closed")
                buf += chunk
            (length,) = _LEN.unpack_from(buf)
            if length > max_frame:
                raise FrameTooLarge(
                    f"frame length prefix {length} exceeds "
                    f"wire_max_frame_bytes ({max_frame})")
            total = _LEN.size + length
            while len(buf) < total:
                chunk = self._sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionClosed("peer closed")
                buf += chunk
            frame = bytes(memoryview(buf)[_LEN.size:total])
            del buf[:total]
            self._handle_frame(frame)

    @staticmethod
    def _log_read_error(name: str, exc: BaseException) -> bool:
        """Shared reader-exit reporting (thread loop + poller): True
        when the exception was recognized and reported."""
        import sys as _sys
        if isinstance(exc, FrameTooLarge):
            _sys.stderr.write(
                f"ray_tpu: killing connection ({name}): {exc}\n")
            return True
        if isinstance(exc, (ConnectionClosed, OSError)):
            return True
        if isinstance(exc, WireVersionError):
            _sys.stderr.write(
                f"ray_tpu: refusing connection ({name}): {exc}\n")
            return True
        return False

    def _read_loop(self) -> None:
        try:
            if self._server and not self._check_auth():
                return
            if native.frame_engine_enabled():
                self._native_read_loop()
            else:
                self._py_read_loop()
        except Exception as e:
            if not self._log_read_error(self.name, e):
                import traceback
                traceback.print_exc()   # handler bug; don't kill silently
        finally:
            self._finish_read()

    def _finish_read(self) -> None:
        """Reader-exit finalization (thread loop finally / poller
        drop): the stream is dead — release fds, fail outstanding
        request futures, fire on_close. Idempotent: the poller and a
        racing close() may both arrive here."""
        with self._finish_lock:
            if self._finished:
                return
            self._finished = True
        self.close()     # reader exit = stream dead; release the fd
        if self._nb_reader is not None:
            self._nb_reader.close()
        if self._pump_sock is not None:
            try:
                self._pump_sock.close()
            except OSError:
                pass
        self._closed.set()
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            fut.set_error(ConnectionClosed("connection lost"))
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                pass

    # ---- poller-driven receiving (r10) ----
    def _attach_pump(self, use_native: bool) -> int:
        """Create this connection's non-blocking pump state and return
        the fd the poller should watch. Both engines read a DUP of the
        socket fd: the dup pins the open file description, so a
        concurrent Connection.close() (shutdown + close of the
        original) surfaces as EOF on the watched fd instead of racing
        fd reuse; the dup is closed in _finish_read."""
        from ray_tpu._private.config import CONFIG
        if use_native:
            self._nb_reader = native.FrameReader(
                self._sock.fileno(), CONFIG.wire_max_frame_bytes)
            return self._nb_reader.fd
        self._pump_sock = socket.socket(
            fileno=os.dup(self._sock.fileno()))
        self._pump_buf = bytearray()
        return self._pump_sock.fileno()

    def _poll_pump(self) -> list[bytes]:
        """Drain readable bytes (never blocking) and return the
        complete frame bodies buffered so far; [] when no complete
        frame is ready yet. Raises ConnectionClosed / FrameTooLarge
        exactly like the blocking read loops."""
        if self._nb_reader is not None:
            try:
                return self._nb_reader.pump_nb()
            except native.PumpClosed:
                raise ConnectionClosed("peer closed") from None
            except native.PumpOversized as e:
                raise FrameTooLarge(str(e)) from None
        from ray_tpu._private.config import CONFIG
        max_frame = CONFIG.wire_max_frame_bytes
        buf = self._pump_buf
        while not self._pump_eof:
            # mirror the C pump: stop reading the moment a complete
            # frame is buffered (the level-triggered poller re-reports
            # the fd while kernel bytes remain)
            if len(buf) >= _LEN.size:
                (length,) = _LEN.unpack_from(buf)
                if length > max_frame:
                    raise FrameTooLarge(
                        f"frame length prefix {length} exceeds "
                        f"wire_max_frame_bytes ({max_frame})")
                if len(buf) >= _LEN.size + length:
                    break
            try:
                chunk = self._pump_sock.recv(1 << 20,
                                             socket.MSG_DONTWAIT)
            except BlockingIOError:
                break
            except OSError as e:
                raise ConnectionClosed(str(e)) from e
            if not chunk:
                self._pump_eof = True
                break
            buf += chunk
        frames = []
        while len(buf) >= _LEN.size:
            (length,) = _LEN.unpack_from(buf)
            if length > max_frame:
                if frames:
                    break      # dispatch what's whole; next pass dies
                raise FrameTooLarge(
                    f"frame length prefix {length} exceeds "
                    f"wire_max_frame_bytes ({max_frame})")
            total = _LEN.size + length
            if len(buf) < total:
                break
            frames.append(bytes(memoryview(buf)[_LEN.size:total]))
            del buf[:total]
        if not frames and self._pump_eof:
            raise ConnectionClosed("peer closed")
        return frames

    def _chaos_flush(self) -> None:
        """Emit frames a healed chaos partition parked for this
        connection (filter_out prepends them to an empty write)."""
        try:
            with self._send_lock:
                self._emit_locked([])
        except ConnectionClosed:
            pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def close(self) -> None:
        ch = _CHAOS_NET
        if ch is not None and ch.active and ch.defer_close(self):
            return                  # partitioned link: no FIN either
        self._closed.set()
        self._lazy_wake.set()       # release the coalescing flusher
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class FlushLoop:
    """Shared collect-then-flush pacer for message-level batching
    buffers (r10: the head-side lease buffer and the agent-side
    completion buffer) — the same window shape as the wire coalescer's
    lazy-queue flusher, factored out so the two sites cannot drift.

    wake() lazily starts a daemon thread, opens a delay_ms-wide
    window, then calls flush_fn(); callers flush inline themselves
    when a count threshold hits. stop() is race-free by construction:
    the dead flag is set BEFORE the event, and the loop re-checks it
    after every wait/sleep, so a stopped owner can never strand the
    thread in wait() forever."""

    def __init__(self, flush_fn: Callable[[], None],
                 delay_ms_fn: Callable[[], float], name: str):
        self._flush = flush_fn
        self._delay_ms = delay_ms_fn
        self._name = name
        self._wake_ev = threading.Event()
        self._dead = False
        self._thread: Optional[threading.Thread] = None
        self._spawn_lock = threading.Lock()

    def wake(self) -> None:
        if self._dead:
            return
        if self._thread is None:
            with self._spawn_lock:
                if self._thread is None and not self._dead:
                    self._thread = threading.Thread(
                        target=self._loop, name=self._name, daemon=True)
                    self._thread.start()
        self._wake_ev.set()

    def stop(self) -> None:
        self._dead = True           # BEFORE the wake: loop must see it
        self._wake_ev.set()

    def _loop(self) -> None:
        while True:
            self._wake_ev.wait()
            if self._dead:
                return
            delay = max(0.0, self._delay_ms() / 1000.0)
            if delay:
                time.sleep(delay)
            self._wake_ev.clear()
            if self._dead:
                return
            try:
                self._flush()
            except Exception:
                pass        # a failed flush must not kill the pacer
                            # (send paths already contain their errors)


class Poller:
    """Process-level read event loop (r10): ONE thread drives the read
    side of every registered connection, replacing thread-per-
    connection reads on the head and agents (reference raylet/GCS run
    their RPC stacks on shared asio event loops the same way).

    Engine: the native epoll API (``rtpu_poller_*`` in core.c —
    epoll_wait blocks with the GIL released, level-triggered, each
    ready fd drained through its connection's C reassembly buffer via
    the MSG_DONTWAIT pump) when the frame engine is on; a
    ``select.select`` Python fallback otherwise (RAY_TPU_DISABLE_NATIVE
    / RAY_TPU_WIRE_NATIVE=0). RAY_TPU_EPOLL=0 disables the loop
    entirely and every connection keeps its own reader thread.

    Liveness rules baked in here:
    - handlers run on the loop thread, so anything that might block on
      another poller-served connection's REPLY must not run here —
      connection teardown (whose on_close callbacks issue blocking
      bundle/cancel RPCs during node death), the cancel_task state op,
      and the lease-revoke hand-back are all dispatched to throwaway
      threads;
    - a connection that dies only kills itself: handler bugs and
      corrupt streams are contained exactly like the per-thread loop.

    Known tradeoff: handlers' plain SENDS (replies, forwarded events)
    still run on the loop thread, so a peer that stops draining its
    socket can stall the whole process's read plane for up to the
    send budget (SO_SNDTIMEO, 30s) instead of one connection's reader
    as under thread-per-connection. The budget bounds the stall and
    then kills the wedged connection; deployments that cannot accept
    it set RAY_TPU_EPOLL=0. Moving the send plane behind per-
    connection outbound queues is the designed escape hatch if this
    ever bites in practice.
    """

    def __init__(self):
        self._use_native = native.frame_engine_enabled()
        self._conns: dict[int, Connection] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake_r, self._wake_w = os.pipe()
        self._ep = None
        if self._use_native:
            self._ep = native.EpollPoller()
            self._ep.add(self._wake_r)
        self._thread = threading.Thread(
            target=self._loop, name="ray-tpu-poller", daemon=True)
        self._thread.start()

    @property
    def alive(self) -> bool:
        return not self._stop.is_set()

    @property
    def engine(self) -> str:
        return "epoll" if self._use_native else "select"

    def register(self, conn: Connection) -> None:
        """Attach a connection's read side to the loop. Falls back to
        the connection's own reader thread on any setup failure (e.g.
        the select() fd limit)."""
        try:
            fd = conn._attach_pump(self._use_native)
            if self._ep is None and fd >= 1024:
                # select() caps at FD_SETSIZE; a bigger fd would make
                # every select call raise. This connection reads on
                # its own thread instead (pump state is closed by
                # _finish_read there).
                raise ValueError("fd exceeds select() FD_SETSIZE")
            # epoll add BEFORE the _conns insert: if the kernel
            # refuses the watch, the thread fallback below must not
            # leave a stale fd->conn mapping behind (a later reuse of
            # that fd number would alias an unrelated connection)
            if self._ep is not None:
                self._ep.add(fd)
            with self._lock:
                self._conns[fd] = conn
            if self._ep is None:
                self._wake()
        except (OSError, ValueError):
            conn._poller = None
            conn._reader.start()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._ep is not None:
                    ready = self._ep.wait(500)
                else:
                    with self._lock:
                        fds = list(self._conns)
                    fds.append(self._wake_r)
                    try:
                        ready, _, _ = _select.select(fds, [], [], 0.5)
                    except (OSError, ValueError):
                        # a fd closed between snapshot and select:
                        # prune dead entries and retry
                        self._prune()
                        continue
            except OSError:
                if self._stop.is_set():
                    return
                time.sleep(0.05)
                continue
            t0 = time.monotonic_ns() if ready else 0
            serviced = False
            for fd in ready:
                if fd == self._wake_r:
                    try:
                        os.read(self._wake_r, 4096)
                    except OSError:
                        pass
                    continue
                with self._lock:
                    conn = self._conns.get(fd)
                if conn is not None:
                    serviced = True
                    self._service(fd, conn)
            if serviced:
                dt = time.monotonic_ns() - t0
                POLLER_STATS["passes"] += 1
                POLLER_STATS["busy_ns"] += dt
                if dt > POLLER_STATS["max_pass_ns"]:
                    POLLER_STATS["max_pass_ns"] = dt

    def _prune(self) -> None:
        """Drop select-fallback entries whose fd died under us."""
        with self._lock:
            items = list(self._conns.items())
        for fd, conn in items:
            try:
                os.fstat(fd)
            except OSError:
                self._drop(fd, conn)

    def _service(self, fd: int, conn: Connection) -> None:
        try:
            frames = conn._poll_pump()
            if frames:
                POLLER_STATS["frames"] += len(frames)
                POLLER_STATS["bytes"] += sum(map(len, frames))
            for frame in frames:
                conn._handle_frame(frame)
        except Exception as e:
            if not Connection._log_read_error(conn.name, e):
                import traceback
                traceback.print_exc()   # handler bug: that conn dies
            self._drop(fd, conn)

    def _drop(self, fd: int, conn: Connection) -> None:
        with self._lock:
            self._conns.pop(fd, None)
        if self._ep is not None:
            try:
                self._ep.remove(fd)
            except OSError:
                pass
        # Teardown OFF the loop thread: on_close callbacks may issue
        # blocking RPCs whose replies arrive over OTHER poller-served
        # connections (node-death -> bundle re-reserve), which would
        # deadlock the loop against itself.
        threading.Thread(target=conn._finish_read,
                         name=f"ray-tpu-conn-close-{conn.name}",
                         daemon=True).start()

    def close(self) -> None:
        """Stop the loop and tear down every still-registered
        connection (their futures must error, not hang)."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._wake()
        if self._thread is not threading.current_thread():
            # an agent's NODE_SHUTDOWN handler runs ON the loop thread
            # (shutdown -> poller.close); joining ourselves raises and
            # the exception used to abort the CALLER's remaining
            # teardown steps (store shutdown, shm sweep) — the loop
            # exits on the stop flag either way
            self._thread.join(timeout=5.0)
        with self._lock:
            conns, self._conns = dict(self._conns), {}
        for fd, conn in conns.items():
            if self._ep is not None:
                try:
                    self._ep.remove(fd)
                except OSError:
                    pass
            threading.Thread(target=conn._finish_read,
                             name=f"ray-tpu-conn-close-{conn.name}",
                             daemon=True).start()
        if self._ep is not None:
            self._ep.close()
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass

    @property
    def num_connections(self) -> int:
        with self._lock:
            return len(self._conns)


def make_poller() -> Optional[Poller]:
    """A process Poller when the epoll loop is enabled (RAY_TPU_EPOLL,
    default on), else None — callers pass the result straight to
    Connection/connect, so EPOLL=0 restores thread-per-connection
    reads everywhere."""
    from ray_tpu._private.config import CONFIG
    if not CONFIG.epoll:
        return None
    try:
        return Poller()
    except OSError:
        return None


class _Future:
    """Minimal thread-safe future for reply correlation."""

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._callbacks: list[Callable[["_Future"], None]] = []
        self._cb_lock = threading.Lock()

    def add_done_callback(self, fn: Callable[["_Future"], None]) -> None:
        """Run `fn(self)` when the reply lands (on the reader thread) —
        relays pipe replies onward without parking a thread. Runs
        immediately if already done."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def _fire(self) -> None:
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:
                pass

    def set(self, value: Any) -> None:
        self._value = value
        self._event.set()
        self._fire()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()
        self._fire()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("rpc timed out")
        if self._error is not None:
            raise self._error
        return self._value


def connect(addr: tuple[str, int],
            handler: Callable[[Connection, dict], None],
            on_close: Optional[Callable[[Connection], None]] = None,
            name: str = "",
            poller: Optional[Poller] = None) -> Connection:
    sock = socket.create_connection(addr)
    conn = Connection(sock, handler, on_close, name=name, poller=poller)
    conn.send_auth()             # no-op unless RAY_TPU_AUTH_TOKEN is set
    conn.start()
    return conn
