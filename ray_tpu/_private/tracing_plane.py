"""Distributed tracing plane: per-process flight recorders + trace
context that rides the wire (SURVEY §5.1, upgraded from shim to
subsystem in r9).

The runtime has had per-plane *counters* since r6-r8 (WIRE_STATS,
OBJECT_PLANE_STATS, head task events), but counters cannot answer
"where did this task's wall-clock go" across driver → head → agent →
worker → object plane. This module provides the three pieces that can:

1. **Flight recorder** — a fixed-size ring of typed span events
   ``(trace_id, span_id, parent_span, kind, name, t0_ns, t1_ns,
   extra)`` with CLOCK_MONOTONIC timestamps, one per process,
   always-on. Appends are a tuple build + one slot store under a lock
   whose critical section is two bytecodes — cheap enough for the
   dispatch hot path — and memory is bounded by ``RAY_TPU_TRACE_RING``
   slots (wraparound overwrites the oldest events; the watermark keeps
   counting so drops are visible). ``RAY_TPU_TRACE=0`` or
   ``RAY_TPU_TRACE_RING=0`` disables recording entirely: emission
   sites gate on :func:`enabled` (memoized per CONFIG generation, the
   same discipline as ``native.frame_engine_enabled``), and disabled
   senders attach no trace context, so envelopes carry zero extra
   bytes.

2. **Trace context** — ``(trace_id, span_id)`` pairs. Within a process
   the current context lives in a threadlocal (:func:`current` /
   :func:`set_current`); across processes it rides the wire in the
   Envelope's optional ``trace_id``/``parent_span`` fields (wire MINOR
   2 — see wire.py; old peers skip the unknown fields per proto3), as
   the message-dict key ``"_trace"``. Span/trace ids are random
   nonzero 63-bit ints (pooled PRNG reseeded at fork, same concern as
   specs.rand_hex).

3. **Export** — :func:`dump` snapshots this process's ring (plus its
   monotonic "now", so a collector can align clocks via the
   request/reply RTT midpoint), and :func:`chrome_trace` turns a list
   of per-process dumps into a Chrome/Perfetto trace-event JSON list:
   one Perfetto process per runtime process, one lane per trace, and
   flow arrows stitching parent → child spans across processes.

Reference parity: the reference's opt-in opentelemetry wrapping
(python/ray/util/tracing_utils) + task_event_buffer.cc execution-truth
timestamps, collapsed into one runtime-owned plane; the export format
is the same chrome://tracing JSON `ray timeline` emits.
"""
from __future__ import annotations

import itertools
import os
import random
import threading
import time
from typing import Any, Iterator, Optional

# ------------------------------------------------------------- ids
_rand = random.Random()


def _reseed() -> None:
    # fork safety: a child inheriting the PRNG state would mint the
    # same span ids as its parent
    _rand.seed()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reseed)


def new_id() -> int:
    """Random nonzero 63-bit id (fits int64 and protobuf fixed64)."""
    while True:
        v = _rand.getrandbits(63)
        if v:
            return v


def now() -> int:
    """Span timestamp: CLOCK_MONOTONIC ns (never wall clock — spans
    must subtract cleanly even when NTP steps the wall clock)."""
    return time.monotonic_ns()


# --------------------------------------------------------- recorder
class FlightRecorder:
    """Fixed-size, lock-light ring of span events.

    Events are immutable tuples; `record` builds one and stores it in
    the next slot (modulo capacity) under a lock held for two
    assignments. The watermark `_n` counts every event ever recorded,
    so `snapshot` knows how many of the oldest were overwritten and
    heartbeats can carry progress without shipping events."""

    __slots__ = ("capacity", "_ring", "_n", "_lock")

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._ring: list = [None] * self.capacity
        self._n = 0
        self._lock = threading.Lock()

    def record(self, kind: str, name: str, t0_ns: int, t1_ns: int,
               trace_id: int = 0, span_id: int = 0,
               parent_span: int = 0,
               extra: Optional[dict] = None) -> None:
        if not self.capacity:
            return
        ev = (trace_id, span_id, parent_span, kind, name,
              t0_ns, t1_ns, extra)
        with self._lock:
            self._ring[self._n % self.capacity] = ev
            self._n += 1

    def watermark(self) -> int:
        """Total events ever recorded (monotonic; rides heartbeats)."""
        return self._n

    def dropped(self) -> int:
        """Events overwritten by wraparound since process start."""
        return max(0, self._n - self.capacity)

    def snapshot(self) -> list:
        """Events oldest → newest (at most `capacity`)."""
        with self._lock:
            n = self._n
            if n <= self.capacity:
                return list(self._ring[:n])
            i = n % self.capacity
            return self._ring[i:] + self._ring[:i]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._n = 0


# ------------------------------------------- process-global recorder
# (gen, recorder, enabled): memoized per CONFIG generation so the
# per-emission gate costs a dict hit, not env lookups. Flip modes
# in-process with env var + CONFIG.reload() (tests, bench A/Bs).
_state: tuple = (-1, FlightRecorder(0), False)
_role = "proc"
_role_name = ""


def set_role(role: str, name: str = "") -> None:
    """Tag this process's dumps (driver / agent / worker + id)."""
    global _role, _role_name
    _role = role
    _role_name = name


def _refresh() -> tuple:
    global _state
    from ray_tpu._private.config import CONFIG
    gen = CONFIG._gen
    st = _state
    if st[0] == gen:
        return st
    cap = int(CONFIG.trace_ring) if CONFIG.trace else 0
    rec = st[1]
    if rec.capacity != cap:
        rec = FlightRecorder(cap)
    _state = (gen, rec, cap > 0)
    return _state


def enabled() -> bool:
    """Whether span emission should run (RAY_TPU_TRACE and a nonzero
    RAY_TPU_TRACE_RING). Hot paths call this before building spans."""
    return _refresh()[2]


def recorder() -> FlightRecorder:
    return _refresh()[1]


def record(kind: str, name: str, t0_ns: int, t1_ns: int,
           trace_id: int = 0, span_id: int = 0, parent_span: int = 0,
           extra: Optional[dict] = None) -> None:
    """Module-level convenience for emission sites that already hold
    the gate result."""
    _refresh()[1].record(kind, name, t0_ns, t1_ns, trace_id, span_id,
                         parent_span, extra)


# Message-dict carrier for the Envelope trace fields: senders attach
# msg[TRACE_KEY] = (trace_id, parent_span); the wire codecs move it
# between the dict and the proto fields (wire.py re-exports this).
TRACE_KEY = "_trace"

# ------------------------------------------------- sampling (r16)
# The head decides once, at submit, whether a ROOT task starts a
# trace (RAY_TPU_TRACE_SAMPLE = stride; 1-in-stride sampled). The
# decision propagates in the existing spec/envelope trace fields, so
# every downstream emission site keeps its r9 gate (`trace_id` truthy
# or a wire-carried ctx) and a sampled task is whole-or-nothing
# across driver, scheduler, agent, worker, and pull manager —
# unsampled tasks record nothing anywhere and their frames are
# byte-identical to RAY_TPU_TRACE=0 frames. The counter is a
# thread-safe itertools.count (deterministic: task k is sampled iff
# k % stride == 0, which the whole-or-nothing test relies on).
_sample_counter = itertools.count()


def sample() -> bool:
    """Head-side sampling decision for a new root trace. stride <= 1
    (incl. the 0 = off revert) keeps the pre-r16 trace-everything
    behavior; the counter only advances for root-submission decisions
    so nested/relayed submissions never skew the stride."""
    from ray_tpu._private.config import CONFIG
    stride = int(CONFIG.trace_sample)
    if stride <= 1:
        return True
    return next(_sample_counter) % stride == 0

# ---------------------------------------------------- trace context
_tls = threading.local()


def current() -> Optional[tuple]:
    """The thread's active (trace_id, span_id), or None."""
    return getattr(_tls, "ctx", None)


def set_current(trace_id: int, span_id: int) -> None:
    _tls.ctx = (trace_id, span_id)


def clear_current() -> None:
    _tls.ctx = None


def wire_ctx() -> Optional[tuple]:
    """The context to attach to an outgoing message's ``"_trace"``
    key, or None when tracing is off / no trace is active."""
    if not enabled():
        return None
    return getattr(_tls, "ctx", None)


def stamp(msg: dict) -> dict:
    """Attach the calling thread's trace context to an outgoing
    message dict (the Envelope codec moves it into the wire's trace
    fields). No-op when tracing is off or no trace is active; returns
    `msg` for call-site chaining."""
    tr = wire_ctx()
    if tr is not None:
        msg[TRACE_KEY] = tr
    return msg


def recv_t0(msg: dict) -> Optional[int]:
    """Receive-side span gate: monotonic now when `msg` carries trace
    context and tracing is on here (the handler records a span with
    this start once its work completes), else None."""
    return now() if (msg.get(TRACE_KEY) and enabled()) else None


class span:
    """Context manager recording one span around a code block.

    Parentage: an explicit ``ctx=(trace_id, parent_span)`` wins, else
    the thread's current context; with neither, the span is recorded
    only when ``root=True`` (which starts a fresh trace — submit,
    broadcast, user annotate) — otherwise the block runs untraced, so
    un-traced operations cost nothing beyond the `enabled` gate.
    Inside the block the current context is this span, so nested
    runtime calls (and their wire messages) parent under it."""

    __slots__ = ("kind", "name", "ctx", "root", "extra",
                 "_tid", "_sid", "_parent", "_t0", "_prev", "_on")

    def __init__(self, kind: str, name: str,
                 ctx: Optional[tuple] = None, root: bool = False,
                 extra: Optional[dict] = None):
        self.kind = kind
        self.name = name
        self.ctx = ctx
        self.root = root
        self.extra = extra
        self._on = False

    def __enter__(self) -> Optional[tuple]:
        if not enabled():
            return None
        cur = self.ctx if self.ctx is not None else current()
        if cur is None or not cur[0]:
            if not self.root:
                return None
            cur = (new_id(), 0)
        self._tid, self._parent = cur[0], cur[1]
        self._sid = new_id()
        self._prev = current()
        _tls.ctx = (self._tid, self._sid)
        self._t0 = now()
        self._on = True
        return (self._tid, self._sid)

    def __exit__(self, *exc) -> None:
        if not self._on:
            return
        _tls.ctx = self._prev
        record(self.kind, self.name, self._t0, now(), self._tid,
               self._sid, self._parent, self.extra)


# ------------------------------------------------------- collection
def fanout_dumps(targets: list, timeout_s: float,
                 extra: Optional[dict] = None,
                 mtype: Optional[str] = None) -> list:
    """TRACE_DUMP fan-out shared by the head and the agents: request
    each ``(meta, connection)`` concurrently, stamp each reply's
    ARRIVAL time the moment it lands (a slow earlier peer must not
    skew a fast later peer's clock offset), and drain under ONE
    shared deadline (N wedged peers cost ~timeout total, not
    N*timeout). `extra` fields ride each request (the head forwards
    its collection budget so agents bound their own worker drain).
    Returns ``[(meta, t0_ns, t1_ns, reply), ...]`` for the replies
    that made it; peers that died or missed the deadline are silently
    absent. `mtype` selects the dump protocol (default TRACE_DUMP; the
    metrics plane reuses this machinery with METRICS_DUMP)."""
    from ray_tpu._private import protocol
    if mtype is None:
        mtype = protocol.TRACE_DUMP
    pending = []
    for meta, conn in targets:
        t0 = now()
        try:
            fut = conn.request_async(
                {"type": mtype, **(extra or {})})
        except protocol.ConnectionClosed:
            continue
        arrival: dict = {}
        fut.add_done_callback(
            lambda f, a=arrival: a.setdefault("t1", now()))
        pending.append((meta, t0, fut, arrival))
    out = []
    deadline = now() + int(timeout_s * 1e9)
    for meta, t0, fut, arrival in pending:
        left = max(0.05, (deadline - now()) / 1e9)
        try:
            rep = fut.result(left)
        except Exception:
            continue
        out.append((meta, t0, arrival.get("t1", now()), rep))
    return out


def dump() -> dict:
    """This process's recorder contents + clock sample, shaped for the
    ``trace_dump`` pull protocol (heartbeats carry only watermarks; the
    events move only when a collector asks)."""
    rec = recorder()
    return {
        "role": _role, "name": _role_name, "pid": os.getpid(),
        "events": rec.snapshot(),
        "watermark": rec.watermark(),
        "dropped": rec.dropped(),
        "capacity": rec.capacity,
        "now_ns": now(),
    }


def rtt_offset(t0_local_ns: int, t1_local_ns: int,
               peer_now_ns: int) -> int:
    """Clock offset of a peer whose dump was requested at local t0 and
    received at local t1: assume the peer sampled `now_ns` at the RTT
    midpoint, so ``peer_clock - local_clock ≈ peer_now - (t0+t1)/2``.
    Subtracting it maps peer timestamps onto the local monotonic
    clock (same-host processes share CLOCK_MONOTONIC, so the residual
    there is just the RTT jitter)."""
    return peer_now_ns - (t0_local_ns + t1_local_ns) // 2


# ----------------------------------------------------------- export
def _iter_spans(processes: list,
                trace_id: Optional[int]) -> Iterator[tuple]:
    for idx, proc in enumerate(processes):
        off = int(proc.get("offset_ns", 0))
        for ev in proc.get("events", ()):
            tid, sid, parent, kind, name, t0, t1, extra = ev
            if trace_id is not None and tid != trace_id:
                continue
            yield (idx, tid, sid, parent, kind, name,
                   t0 - off, t1 - off, extra)


def chrome_trace(processes: list,
                 trace_id: Optional[int] = None) -> list:
    """Chrome/Perfetto trace-event list from per-process dumps (as
    returned by the ``trace_dump`` state op). One Perfetto process per
    runtime process, one lane (tid) per trace_id, spans as complete
    ("X") events, and a flow arrow ("s"/"f" pair) for every
    parent→child edge whose two ends are present — every emitted flow is
    therefore begin+end complete by construction."""
    spans = list(_iter_spans(processes, trace_id))
    out: list = []
    for idx, proc in enumerate(processes):
        label = (f"{proc.get('role', 'proc')} "
                 f"{proc.get('name', '')}".strip()
                 + f" (pid {proc.get('pid', '?')})")
        out.append({"ph": "M", "name": "process_name", "pid": idx + 1,
                    "tid": 0, "args": {"name": label}})
    if not spans:
        return out
    base = min(s[6] for s in spans)
    by_sid: dict = {}
    rows = []
    for idx, tid, sid, parent, kind, name, t0, t1, extra in spans:
        lane = tid % 1_000_000 if tid else 0
        ts = (t0 - base) / 1e3                      # µs
        dur = max((t1 - t0) / 1e3, 0.001)
        rows.append((idx + 1, lane, ts, dur, sid, parent, kind, name,
                     tid, extra))
        if sid:
            by_sid[sid] = (idx + 1, lane, ts)
    for pid, lane, ts, dur, sid, parent, kind, name, tid, extra in rows:
        args = {"trace_id": f"{tid:x}", "span_id": f"{sid:x}",
                "parent_span": f"{parent:x}"}
        if extra:
            args.update({k: str(v) for k, v in extra.items()})
        out.append({"name": name, "cat": kind, "ph": "X",
                    "pid": pid, "tid": lane, "ts": round(ts, 3),
                    "dur": round(dur, 3), "args": args})
        src = by_sid.get(parent)
        if src is not None and sid:
            s_pid, s_lane, s_ts = src
            out.append({"ph": "s", "id": str(sid), "name": "parent",
                        "cat": "flow", "pid": s_pid, "tid": s_lane,
                        "ts": round(s_ts + 0.001, 3)})
            out.append({"ph": "f", "bp": "e", "id": str(sid),
                        "name": "parent", "cat": "flow", "pid": pid,
                        "tid": lane, "ts": round(ts + 0.001, 3)})
    return out
