"""Driver-side runtime: the core-worker + head-node composition.

This process plays three reference roles at once (single-node topology):
- the driver's core worker (reference src/ray/core_worker/core_worker.cc:
  SubmitTask:2166, CreateActor:2243, Put:1246, Get:1551),
- the GCS head (tables live in ``Controller``),
- the raylet (dispatch lives in ``Scheduler``).

Multi-process reality is preserved where it matters — user tasks and actors
always run in separate worker processes wired over the socket protocol, and
bulk data rides shared memory — so the concurrency/failure semantics match
the reference even though control-plane hops are function calls.
"""
from __future__ import annotations

import glob
import os
import socket
import threading
import time
from typing import Any, Optional

from ray_tpu._private import context as _context
from ray_tpu._private import protocol
from ray_tpu._private.controller import (ALIVE, DEAD, PENDING, RESTARTING,
                                         Controller)
from ray_tpu._private.object_store import LocalStore, StoredObject, deserialize
from ray_tpu._private.refs import ObjectRef
from ray_tpu._private.scheduler import Scheduler
from ray_tpu._private.specs import ActorSpec, ActorTaskSpec, TaskSpec
from ray_tpu.exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                                TaskCancelledError, TaskError,
                                WorkerDiedError)


def detect_num_tpu_chips() -> int:
    """TPU chip detection, reference python/ray/_private/accelerators/tpu.py:98-117
    (probes /dev/accel* then /dev/vfio), with an env override."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env is not None:
        return int(env)
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob.glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


class _ActorState:
    """Driver-side actor-task routing state (actor_task_submitter.cc parity:
    per-actor ordered queue while the actor is pending/restarting, inflight
    tracking for failure handling)."""

    def __init__(self):
        self.queued: list[ActorTaskSpec] = []
        self.inflight: dict[str, ActorTaskSpec] = {}
        self.lock = threading.Lock()


class Runtime(_context.BaseContext):
    is_driver = True

    def __init__(self, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None,
                 max_workers: Optional[int] = None,
                 namespace: str = "default"):
        self.namespace = namespace
        self.controller = Controller()
        # capacity via RAY_TPU_OBJECT_STORE_MEMORY (bytes); spill policy
        # must never touch objects pinned by in-flight tasks.
        self.store = LocalStore(pinned_fn=self.controller.pinned_ids)
        from concurrent.futures import ThreadPoolExecutor
        from ray_tpu._private.waiters import WaiterRegistry
        # Blocked worker gets/waits park here (no thread each); the
        # store's seal hook resolves them. Spill restores run on a small
        # pool so disk reads never block connection reader threads.
        self.waiters = WaiterRegistry(self.store.contains)
        self.store.on_seal = self.waiters.notify
        self._restore_pool = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="rtpu-restore")
        self._shutdown = False
        self._actor_states: dict[str, _ActorState] = {}
        self._actor_lock = threading.Lock()

        if num_cpus is None:
            num_cpus = float(max(os.cpu_count() or 1, 4))
        if num_tpus is None:
            num_tpus = float(detect_num_tpu_chips())
        node_res = {"CPU": float(num_cpus)}
        if num_tpus:
            node_res["TPU"] = float(num_tpus)
        from ray_tpu._private.config import CONFIG as _CFG
        node_res["memory"] = float(
            os.environ.get("RAY_TPU_NODE_MEMORY")    # legacy name
            or _CFG.node_memory_bytes)
        if resources:
            node_res.update({k: float(v) for k, v in resources.items()})

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(128)
        self.address = self._listener.getsockname()

        from ray_tpu._private.cluster import ClusterTaskManager
        self.cluster = ClusterTaskManager(self)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ray-tpu-accept", daemon=True)
        self._accept_thread.start()
        head = self.cluster.add_node(node_res, max_workers=max_workers,
                                     is_head=True)
        self.head_node_id = head.node_id

    @property
    def scheduler(self):
        """The head node's scheduler (single-node compatibility view)."""
        rec = self.cluster.get_node(self.head_node_id)
        return rec.scheduler if rec else None

    def _scheduler_for_worker(self, worker_id: str):
        return self.cluster.scheduler_for_worker(worker_id)

    # ================= connection plumbing =================
    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = protocol.Connection(sock, self._handle_msg,
                                       self._on_conn_closed, name="driver")
            conn.start()

    def _on_conn_closed(self, conn: protocol.Connection) -> None:
        wid = conn.meta.get("worker_id")
        if wid is None or self._shutdown:
            return
        sched = self._scheduler_for_worker(wid)
        if sched is None:
            return
        task, actor_id = sched.on_worker_lost(wid)
        if task is not None:
            self._recover_task(task)
        if actor_id is not None:
            self._recover_actor(actor_id)

    # ================= failure recovery =================
    def _recover_task(self, spec: TaskSpec) -> None:
        """Reference parity: task retries on worker failure
        (task_manager.cc retry bookkeeping; max_retries option)."""
        if getattr(spec, "cancelled", False):
            self._store_error(spec.return_ids, TaskError(
                TaskCancelledError(spec.task_id), task_name=spec.name))
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(
                spec.task_id, spec.name, "CANCELLED")
            return
        if spec.retries_used < spec.max_retries:
            spec.retries_used += 1
            self.controller.record_task_event(
                spec.task_id, spec.name, "RETRYING")
            self.cluster.submit(spec)
        else:
            err = TaskError(WorkerDiedError(
                f"worker died running task {spec.name or spec.task_id}"),
                task_name=spec.name)
            self._store_error(spec.return_ids, err)
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(
                spec.task_id, spec.name, "FAILED", error="worker died")

    def _recover_actor(self, actor_id: str) -> None:
        """GcsActorManager restart-on-failure parity
        (gcs_actor_manager.h:89-91 max_restarts bookkeeping)."""
        rec = self.controller.get_actor(actor_id)
        if rec is None or rec.state == DEAD:
            return
        st = self._actor_state(actor_id)
        with st.lock:
            inflight = list(st.inflight.values())
            st.inflight.clear()
        can_restart = (rec.spec.max_restarts < 0
                       or rec.num_restarts < rec.spec.max_restarts)
        if can_restart:
            rec.num_restarts += 1
            self.controller.set_actor_state(actor_id, RESTARTING)
            retried = []
            for t in inflight:           # preserve submission order
                if t.retries_used < t.max_retries:
                    t.retries_used += 1
                    retried.append(t)
                else:
                    self._store_error(t.return_ids, TaskError(
                        ActorError(actor_id, "actor restarting; task lost"),
                        task_name=t.name))
            with st.lock:
                st.queued[:0] = retried
            self.cluster.submit(rec.spec)
        else:
            self.controller.set_actor_state(actor_id, DEAD,
                                            death_cause="worker died")
            with st.lock:
                dead_tasks = inflight + st.queued
                st.queued = []
            for t in dead_tasks:
                self._store_error(t.return_ids, TaskError(
                    ActorDiedError(actor_id, f"Actor {actor_id} is dead"),
                    task_name=t.name))

    def _store_error(self, return_ids: list[str], err: BaseException) -> None:
        for oid in return_ids:
            self.store.put(err, object_id=oid)

    def on_unplaceable(self, spec, reason: str) -> None:
        """Cluster callback: a spec can never be placed (e.g. hard node
        affinity to a dead node). Fail fast rather than hang."""
        from ray_tpu._private.specs import ActorSpec as _ActorSpec
        if isinstance(spec, _ActorSpec):
            self.controller.set_actor_state(spec.actor_id, DEAD,
                                            death_cause=reason)
            st = self._actor_state(spec.actor_id)
            with st.lock:
                dead = st.queued + list(st.inflight.values())
                st.queued = []
                st.inflight.clear()
            for t in dead:
                self._store_error(t.return_ids, TaskError(
                    ActorDiedError(spec.actor_id, reason),
                    task_name=t.name))
            return
        self._store_error(spec.return_ids, TaskError(
            WorkerDiedError(f"task unplaceable: {reason}"),
            task_name=spec.name))
        self._unpin(spec.pinned_refs)
        self.controller.record_task_event(spec.task_id, spec.name,
                                          "FAILED", error=reason)

    def _unpin(self, object_ids: list[str]) -> None:
        for oid in object_ids:
            if self.controller.unpin(oid):
                self.store.delete(oid)

    # ================= scheduler callbacks =================
    def on_task_dispatched(self, spec: TaskSpec, worker_id: str) -> None:
        self.controller.record_task_event(
            spec.task_id, spec.name, "RUNNING", worker_id=worker_id)

    def on_actor_dispatched(self, spec: ActorSpec, worker_id: str) -> None:
        self.controller.set_actor_state(spec.actor_id, PENDING,
                                        worker_id=worker_id)

    # ================= message handlers =================
    def _handle_msg(self, conn: protocol.Connection, msg: dict) -> None:
        mtype = msg["type"]
        if mtype == protocol.REGISTER:
            sched = self._scheduler_for_worker(msg["worker_id"])
            if sched is not None:
                sched.on_worker_registered(msg["worker_id"], conn)
            else:
                conn.close()              # worker from a dead/old node
        elif mtype == protocol.TASK_DONE:
            self._on_task_done(conn, msg)
        elif mtype == protocol.GET_OBJECT:
            self._on_get_object(conn, msg)
        elif mtype == protocol.WAIT:
            self._on_wait(conn, msg)
        elif mtype == protocol.PUT_OBJECT:
            stored: StoredObject = msg["stored"]
            self.store.put_stored(stored)
            self.controller.addref(stored.object_id)
            conn.reply(msg, ok=True)
        elif mtype == protocol.SUBMIT:
            spec: TaskSpec = msg["spec"]
            if msg.get("func_bytes") is not None:
                self.controller.put_function(spec.func_id, msg["func_bytes"])
            self.submit_spec(spec)
            conn.reply(msg, ok=True)
        elif mtype == protocol.SUBMIT_ACTOR:
            aspec: ActorSpec = msg["spec"]
            if msg.get("class_bytes") is not None:
                self.controller.put_function(aspec.class_id,
                                             msg["class_bytes"])
            self.create_actor_from_spec(aspec)
            conn.reply(msg, ok=True)
        elif mtype == protocol.SUBMIT_ACTOR_TASK:
            self.submit_actor_task_spec(msg["actor_id"], msg["spec"])
            conn.reply(msg, ok=True)
        elif mtype == protocol.KV_OP:
            conn.reply(msg, value=self._kv_dispatch(msg))
        elif mtype == protocol.DECREF:
            self.decref(msg["object_id"])
        elif mtype == protocol.ADDREF:
            self.controller.addref(msg["object_id"])
        elif mtype == protocol.STATE_OP:
            conn.reply(msg, value=self.state_op(msg["op"], **msg.get(
                "kwargs", {})))
        elif mtype == protocol.PING:
            conn.reply(msg, ok=True)

    def _on_task_done(self, conn: protocol.Connection, msg: dict) -> None:
        results: list[StoredObject] = msg.get("results", [])
        for stored in results:
            self.store.put_stored(stored)
            # Fire-and-forget results whose refs were already dropped must
            # be evicted here, or they accumulate until shutdown.
            if self.controller.unreferenced(stored.object_id):
                self.store.delete(stored.object_id)
        worker_id = conn.meta.get("worker_id", "")
        wsched = self._scheduler_for_worker(worker_id)
        if msg.get("is_actor_create"):
            actor_id = msg["actor_id"]
            if wsched is not None:
                wsched.actor_ready(worker_id)
            if msg.get("error"):
                rec = self.controller.get_actor(actor_id)
                if rec is not None:
                    rec.spec.max_restarts = 0  # init failure is terminal
                self.controller.set_actor_state(
                    actor_id, DEAD, death_cause="creation failed")
                st = self._actor_state(actor_id)
                with st.lock:
                    dead = st.queued
                    st.queued = []
                cause = msg.get("error_repr", "actor __init__ raised")
                for t in dead:
                    self._store_error(t.return_ids, TaskError(
                        ActorDiedError(actor_id, cause), task_name=t.name))
            else:
                self.controller.set_actor_state(actor_id, ALIVE,
                                                worker_id=worker_id)
                self._flush_actor_queue(actor_id)
            return
        task_id = msg["task_id"]
        if msg.get("is_actor_task"):
            st = self._actor_states.get(msg.get("actor_id", ""))
            if st is not None:
                with st.lock:
                    spec = st.inflight.pop(task_id, None)
                if spec is not None:
                    self._unpin(spec.pinned_refs)
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(task_id, msg.get("name", ""),
                                              state, worker_id=worker_id)
            return
        spec = (wsched.task_finished(worker_id)
                if wsched is not None else None)
        if spec is not None:
            self._unpin(spec.pinned_refs)
            state = "FAILED" if msg.get("error") else "FINISHED"
            self.controller.record_task_event(spec.task_id, spec.name, state,
                                              worker_id=worker_id)

    def _on_get_object(self, conn: protocol.Connection, msg: dict) -> None:
        """Event-driven get: a fast residency probe on the reader
        thread; on miss the request parks in the waiter registry (no
        thread) and the put_stored seal hook resolves it. Spilled
        objects restore on a small worker pool so the disk read never
        runs on a connection reader thread."""
        oid = msg["object_id"]
        stored = self.store.get_stored(oid, timeout=0, restore=False)
        if stored is not None:
            conn.reply(msg, stored=stored)
            return
        timeout = msg.get("timeout")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        wid = conn.meta.get("worker_id")
        wsched = self._scheduler_for_worker(wid) if wid else None
        if self.store.contains(oid):
            self._restore_pool.submit(
                self._blocking_get_reply, conn, msg, oid, deadline,
                wsched, wid)
            return
        if wsched is not None:
            wsched.worker_blocked(wid)

        def reply(w, timed_out: bool) -> None:
            try:
                if timed_out:
                    conn.reply(msg, stored=None, timeout=True)
                    return
                got = self.store.get_stored(oid, timeout=0, restore=False)
                if got is not None:
                    conn.reply(msg, stored=got)
                elif self.store.contains(oid):
                    # sealed then instantly spilled: remaining budget only
                    self._restore_pool.submit(
                        self._blocking_get_reply, conn, msg, oid,
                        deadline, wsched, wid)
                else:
                    # sealed then evicted in the gap: genuine miss
                    conn.reply(msg, stored=None, timeout=True)
            except protocol.ConnectionClosed:
                pass

        self.waiters.add_get(
            oid, reply, timeout,
            on_done=((lambda: wsched.worker_unblocked(wid))
                     if wsched is not None else None))

    def _blocking_get_reply(self, conn, msg, oid,
                            deadline: Optional[float],
                            wsched=None, wid=None) -> None:
        """Restore-pool path: blocking fetch (may read a spill file).
        The worker stays marked blocked for the duration so its
        scheduler slot is released (oversubscription parity with the
        old thread-per-get path)."""
        if wsched is not None:
            wsched.worker_blocked(wid)
        try:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            got = self.store.get_stored(oid, timeout=remaining)
            if got is not None:
                conn.reply(msg, stored=got)
            else:
                conn.reply(msg, stored=None, timeout=True)
        except protocol.ConnectionClosed:
            pass
        finally:
            if wsched is not None:
                wsched.worker_unblocked(wid)

    def _on_wait(self, conn: protocol.Connection, msg: dict) -> None:
        ids, num_returns = msg["object_ids"], msg["num_returns"]
        ready_now = [o for o in ids if self.store.contains(o)]
        if len(ready_now) >= num_returns:
            conn.reply(msg, ready=ready_now[:num_returns])
            return
        wid = conn.meta.get("worker_id")
        wsched = self._scheduler_for_worker(wid) if wid else None
        if wsched is not None:
            wsched.worker_blocked(wid)

        def reply(w, ready: list[str]) -> None:
            try:
                conn.reply(msg, ready=ready[:num_returns])
            except protocol.ConnectionClosed:
                pass

        self.waiters.add_wait(
            ids, num_returns, reply, msg.get("timeout"),
            on_done=((lambda: wsched.worker_unblocked(wid))
                     if wsched is not None else None))

    def _kv_dispatch(self, msg: dict) -> Any:
        op = msg["op"]
        ns = msg.get("namespace", "default")
        key = msg.get("key", "")
        if op == "get":
            return self.controller.kv_get(key, ns)
        if op == "put":
            return self.controller.kv_put(key, msg.get("value"), ns,
                                          msg.get("overwrite", True))
        if op == "del":
            return self.controller.kv_del(key, ns)
        if op == "exists":
            return self.controller.kv_exists(key, ns)
        if op == "keys":
            return self.controller.kv_keys(key, ns)
        if op == "func_get":
            return self.controller.get_function(key)
        raise ValueError(f"unknown kv op {op}")

    # ================= BaseContext API (driver) =================
    def put(self, value: Any) -> ObjectRef:
        oid = self.store.put(value)
        self.controller.addref(oid)
        return ObjectRef(oid)

    def get_objects(self, object_ids: list[str],
                    timeout: Optional[float]) -> list[Any]:
        deadline = None if timeout is None else time.time() + timeout
        out = []
        for oid in object_ids:
            remaining = None if deadline is None else max(
                0.0, deadline - time.time())
            stored = self.store.get_stored(oid, timeout=remaining)
            if stored is None:
                raise GetTimeoutError(
                    f"get() timed out waiting for {oid}")
            try:
                value = deserialize(stored)
            except FileNotFoundError:
                # The spill policy unlinked this object's shm between
                # get_stored and the map (rare: touch-grace usually
                # prevents it). The data lives in the spill file —
                # re-fetch; the restore comes back with inline buffers.
                stored = self.store.get_stored(oid, timeout=remaining)
                if stored is None:
                    raise GetTimeoutError(
                        f"get() timed out waiting for {oid}")
                value = deserialize(stored)
            if stored.is_error:
                raise value
            out.append(value)
        return out

    def wait(self, object_ids: list[str], num_returns: int,
             timeout: Optional[float]) -> tuple[list[str], list[str]]:
        ready = self.store.wait_any(object_ids, num_returns, timeout)
        # Contract: at most num_returns in the ready list (reference
        # ray.wait semantics), in input order.
        ready_set = set(ready)
        ready_list = [o for o in object_ids if o in ready_set][:num_returns]
        taken = set(ready_list)
        not_ready = [o for o in object_ids if o not in taken]
        return ready_list, not_ready

    def addref(self, object_id: str) -> None:
        self.controller.addref(object_id)

    def decref(self, object_id: str) -> None:
        if self._shutdown:
            return
        if self.controller.decref(object_id):
            self.store.delete(object_id)

    def submit_spec(self, spec: TaskSpec) -> list[str]:
        for oid in spec.pinned_refs:
            self.controller.pin(oid)
        self.controller.record_task_event(spec.task_id, spec.name, "PENDING")
        self.cluster.submit(spec)
        return spec.return_ids

    submit_task = submit_spec

    def register_function(self, func_id: str, data: bytes) -> None:
        self.controller.put_function(func_id, data)

    # ---- actors ----
    def _actor_state(self, actor_id: str) -> _ActorState:
        with self._actor_lock:
            st = self._actor_states.get(actor_id)
            if st is None:
                st = self._actor_states[actor_id] = _ActorState()
            return st

    def create_actor_from_spec(self, spec: ActorSpec) -> str:
        self.controller.register_actor(spec)
        self._actor_state(spec.actor_id)
        self.cluster.submit(spec)
        return spec.actor_id

    create_actor = create_actor_from_spec

    def submit_actor_task_spec(self, actor_id: str,
                               spec: ActorTaskSpec) -> list[str]:
        for oid in spec.pinned_refs:
            self.controller.pin(oid)
        rec = self.controller.get_actor(actor_id)
        if rec is None:
            self._store_error(spec.return_ids, TaskError(
                ActorError(actor_id, "unknown actor"), task_name=spec.name))
            return spec.return_ids
        st = self._actor_state(actor_id)
        with st.lock:
            if rec.state == DEAD:
                self._store_error(spec.return_ids, TaskError(
                    ActorDiedError(actor_id,
                                   f"Actor {actor_id} is dead: "
                                   f"{rec.death_cause}"),
                    task_name=spec.name))
                return spec.return_ids
            if rec.state != ALIVE or rec.worker_id is None:
                st.queued.append(spec)
                return spec.return_ids
            st.inflight[spec.task_id] = spec
            target = rec.worker_id
        if not self._send_actor_task(target, spec):
            with st.lock:
                # Requeue only if a concurrent _recover_actor didn't already
                # claim it from inflight (else it would run twice).
                if st.inflight.pop(spec.task_id, None) is not None:
                    st.queued.append(spec)
        return spec.return_ids

    submit_actor_task = submit_actor_task_spec

    def _send_actor_task(self, worker_id: str, spec: ActorTaskSpec) -> bool:
        sched = self._scheduler_for_worker(worker_id)
        if sched is None:
            return False
        return sched.send_actor_task(worker_id, spec)

    def _flush_actor_queue(self, actor_id: str) -> None:
        rec = self.controller.get_actor(actor_id)
        if rec is None or rec.state != ALIVE:
            return
        st = self._actor_state(actor_id)
        while True:
            with st.lock:
                if not st.queued:
                    return
                spec = st.queued.pop(0)
                st.inflight[spec.task_id] = spec
                target = rec.worker_id
            if not self._send_actor_task(target, spec):
                with st.lock:
                    st.inflight.pop(spec.task_id, None)
                    st.queued.insert(0, spec)
                return

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        rec = self.controller.get_actor(actor_id)
        if rec is None:
            return
        if no_restart:
            rec.spec.max_restarts = 0
        wid = rec.worker_id
        if wid is not None:
            sched = self._scheduler_for_worker(wid)
            if sched is not None:
                sched.kill_worker(wid)

    def cancel_task(self, object_id: str, force: bool = False) -> None:
        """Cancel a task by its return ref (reference core_worker
        CancelTask): queued tasks are removed; RUNNING tasks get
        TaskCancelledError raised in their executor thread, or their
        worker killed outright with force=True. Either way the task is
        marked non-retriable first so worker-death recovery doesn't
        resurrect it."""
        # Return ids are "<task_id>r<i>" and task ids are hex, so 'r' splits.
        task_id = object_id.split("r", 1)[0]
        for node in self.cluster.alive_nodes():
            spec = node.scheduler.cancel_pending(task_id)
            if spec is not None:
                err = TaskCancelledError(task_id)
                self._store_error(spec.return_ids, TaskError(
                    err, task_name=spec.name))
                self._unpin(spec.pinned_refs)
                self.controller.record_task_event(task_id, spec.name,
                                                  "CANCELLED")
                return
        # parked as infeasible (autoscaler may be provisioning)?
        spec = self.cluster.cancel_parked(task_id)
        if spec is not None:
            self._store_error(spec.return_ids, TaskError(
                TaskCancelledError(task_id), task_name=spec.name))
            self._unpin(spec.pinned_refs)
            self.controller.record_task_event(task_id, spec.name,
                                              "CANCELLED")
            return
        # not queued: running somewhere?
        for node in self.cluster.alive_nodes():
            hit = node.scheduler.worker_running_task(task_id)
            if hit is None:
                continue
            worker_id, spec = hit
            spec.cancelled = True        # no retry on worker death
            self.controller.record_task_event(task_id, spec.name,
                                              "CANCELLING")
            if force:
                node.scheduler.kill_worker(worker_id)
            else:
                node.scheduler.cancel_running(worker_id, task_id)
            return

    def get_actor_handle(self, name: str, namespace: str = "default"):
        actor_id = self.controller.get_named_actor(name, namespace)
        if actor_id is None:
            raise ValueError(f"No actor named {name!r} in namespace "
                             f"{namespace!r}")
        rec = self.controller.get_actor(actor_id)
        from ray_tpu.actor import ActorHandle
        import pickle as _p
        cls = _p.loads(self.controller.get_function(rec.spec.class_id))
        return ActorHandle._from_class(actor_id, cls,
                                       rec.spec.max_task_retries)

    # ---- state / introspection ----
    def state_op(self, op: str, **kwargs) -> Any:
        if op == "list_actors":
            return self.controller.list_actors()
        if op == "list_tasks":
            return self.controller.list_task_events(
                kwargs.get("limit", 1000))
        if op == "summarize_tasks":
            return self.controller.summarize_tasks()
        if op == "list_placement_groups":
            return self.cluster.pg_table()
        if op == "list_nodes":
            return self.controller.list_nodes()
        if op == "cluster_resources":
            return self.cluster.total_resources()
        if op == "available_resources":
            return self.cluster.available_resources()
        if op == "scheduler_stats":
            return self.scheduler.stats()
        if op == "cluster_stats":
            return self.cluster.stats()
        if op == "object_store_stats":
            return self.store.stats()
        if op == "waiter_stats":
            return self.waiters.stats()
        if op == "pubsub_poll":
            return self.controller.pubsub.poll(
                kwargs["channel"], kwargs.get("cursor", 0),
                kwargs.get("timeout"))
        if op == "pubsub_publish":
            return self.controller.pubsub.publish(
                kwargs["channel"], kwargs["message"])
        if op == "cancel_task":
            self.cancel_task(kwargs["object_id"],
                             kwargs.get("force", False))
            return True
        if op == "kill_actor":
            self.kill_actor(kwargs["actor_id"],
                            kwargs.get("no_restart", True))
            return True
        raise ValueError(f"unknown state op {op}")

    def node_resources(self) -> dict:
        return dict(self.scheduler.total)

    # ---- lifecycle ----
    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        self.cluster.shutdown()
        self.waiters.shutdown()
        self._restore_pool.shutdown(wait=False)
        try:
            self._listener.close()
        except OSError:
            pass
        self.store.shutdown()


# ================= module-level init/shutdown =================
def init(num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
         resources: Optional[dict] = None, max_workers: Optional[int] = None,
         namespace: str = "default",
         ignore_reinit_error: bool = False) -> Runtime:
    existing = _context.maybe_ctx()
    if existing is not None:
        if ignore_reinit_error:
            return existing  # type: ignore[return-value]
        if existing.is_driver:
            raise RuntimeError("ray_tpu.init() called twice; pass "
                               "ignore_reinit_error=True to allow this.")
        return existing  # inside a worker: init is a no-op, like ray.init
    rt = Runtime(num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
                 max_workers=max_workers, namespace=namespace)
    _context.set_ctx(rt)
    return rt


def shutdown() -> None:
    ctx = _context.maybe_ctx()
    if ctx is not None and isinstance(ctx, Runtime):
        ctx.shutdown()
        _context.set_ctx(None)
